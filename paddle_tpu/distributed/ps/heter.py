"""Heterogeneous parameter-server training (SURVEY §2 row 33).

Reference: paddle/fluid/framework/fleet/heter_ps/ — heter_comm.h:1
(cross-tier gradient/value movement with dedicated copy streams),
heter_ps.h (sparse tables on the capacious CPU/host tier while dense
math runs on the accelerator tier), heter_section_worker.cc (the split
trainer loop).

TPU-native redesign: the heterogeneous split maps onto host-DRAM PS
servers (distributed/ps — the C++ table fleet; terabytes of cheap
memory) for the UNBOUNDED sparse state, and one jitted XLA program on
the TPU for everything dense. A step is:

    pull_sparse(keys)  ->  [jit] segment-pool + dense fwd/bwd, with the
    (host tier)             pulled rows as INPUTS and their gradient as
                            an OUTPUT (the dense update applies inside)
                       ->  push_sparse(keys, row_grads)   (async)

The pull for batch k+1 overlaps the device step for batch k via a
prefetch thread, and the push for batch k overlaps batch k+1 — the
copy-stream overlap heter_comm implements with CUDA streams. Sparse
rows are padded to a power-of-two capacity so ONE compiled program
serves every batch (XLA static shapes); the pad rows are masked out of
both the pool and the pushed gradient.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

__all__ = ["HeterTrainer"]


def _pad_capacity(n: int, minimum: int = 128) -> int:
    c = minimum
    while c < n:
        c *= 2
    return c


class HeterTrainer:
    """Train `dense_model` on the accelerator against sparse embeddings
    living on the PS host tier.

    dense_model.forward(pooled [B, emb_dim], feats [B, F]) -> logits;
    `loss_fn(logits, labels) -> scalar` (defaults to softmax CE via
    nn.functional). The sparse table updates with plain SGD on the
    servers (the reference's sparse SGD rule); the dense params update
    with `optimizer` inside the jitted step."""

    def __init__(self, client, dense_model, emb_dim, optimizer, table=0,
                 lr_sparse=0.1, loss_fn=None, create_table=True):
        self.client = client
        self.model = dense_model
        self.emb_dim = int(emb_dim)
        self.table = int(table)
        self.lr_sparse = float(lr_sparse)
        self.optimizer = optimizer
        if create_table:
            client.create_sparse_table(self.table, self.emb_dim)
        if loss_fn is None:
            from ...nn import functional as F

            def loss_fn(logits, labels):
                return F.cross_entropy(logits, labels)
        self._loss_fn = loss_fn
        self._jits = {}          # capacity -> compiled step
        self._params = {k: v._data for k, v in
                        dense_model.named_parameters()}
        self._opt_state = optimizer.functional_init(self._params)
        self._push_pending = None      # (keys, device row-grads)
        # one socket, two threads (prefetch pulls + main-thread pushes):
        # RPCs serialize on this lock — the OVERLAP we are after is
        # host-RPC vs device-compute, which the lock does not hinder
        self._net_lock = threading.Lock()

    # -- the jitted dense step --------------------------------------------

    def _jitted(self, capacity, B):
        import jax
        import jax.numpy as jnp

        from ...framework import functional_call

        key = (capacity, B)
        if key in self._jits:
            return self._jits[key]
        model = self.model
        loss_fn = self._loss_fn
        opt = self.optimizer

        def step(params, opt_state, rows, seg, valid, feats, labels):
            def loss_of(p, r):
                # masked segment-sum pool: pad rows fall into segment B
                # (dropped); valid scales real rows by 1.0
                pooled = jax.ops.segment_sum(
                    r * valid[:, None], seg, num_segments=B + 1)[:B]
                out, _ = functional_call(model, p, {}, pooled, feats,
                                         mutable_state=False)
                from ...core.tensor import Tensor
                lval = loss_fn(Tensor(out) if not hasattr(out, "_data")
                               else out, Tensor(labels))
                return (lval._data if hasattr(lval, "_data")
                        else lval).astype(jnp.float32)

            (loss), (gp, grows) = jax.value_and_grad(
                loss_of, argnums=(0, 1))(params, rows)
            new_p, new_opt = opt.functional_update(params, gp, opt_state)
            return loss, new_p, new_opt, grows

        self._jits[key] = jax.jit(step)
        return self._jits[key]

    # -- one training step -------------------------------------------------

    def step(self, keys, lod, feats, labels, rows=None):
        """keys: flat uint64 ids; lod: [B+1] offsets (MultiSlot feed
        layout); feats [B, F] f32; labels [B] int64. `rows` lets the
        prefetch path hand in already-pulled values."""
        import jax.numpy as jnp

        keys = np.ascontiguousarray(keys, np.uint64).ravel()
        lod = np.asarray(lod, np.int64)
        B = len(lod) - 1
        n = keys.size
        # drain the previous step's sparse push BEFORE pulling rows it
        # would touch: direct synchronous step() loops see at most the
        # documented one-step-stale values (prefetched `rows` callers
        # accept the wider train()-loop staleness below)
        self.flush()
        if rows is None:
            with self._net_lock:
                rows = self.client.pull_sparse(self.table, keys,
                                               self.emb_dim)
        cap = _pad_capacity(n)
        rows_p = np.zeros((cap, self.emb_dim), np.float32)
        rows_p[:n] = rows
        seg = np.full((cap,), B, np.int32)     # pad -> dropped segment
        seg[:n] = np.repeat(np.arange(B, dtype=np.int32),
                            np.diff(lod).astype(np.int64))
        valid = np.zeros((cap,), np.float32)
        valid[:n] = 1.0

        fn = self._jitted(cap, B)
        loss, self._params, self._opt_state, grows = fn(
            self._params, self._opt_state, jnp.asarray(rows_p),
            jnp.asarray(seg), jnp.asarray(valid),
            jnp.asarray(np.asarray(feats, np.float32)),
            jnp.asarray(np.asarray(labels)))
        self._push_pending = (keys, grows, n)
        return loss

    def flush(self):
        """Complete the outstanding sparse push (host-side)."""
        if self._push_pending is None:
            return
        keys, grows, n = self._push_pending
        self._push_pending = None
        g = np.asarray(grows)[:n]
        with self._net_lock:
            self.client.push_sparse(self.table, keys, g, self.lr_sparse)

    # -- prefetch-overlapped epoch loop ------------------------------------

    def train(self, batches, epochs=1):
        """batches: a reusable iterable, or a zero-arg callable returning
        one, of (keys, lod, feats, labels). The pull for batch k+1 runs
        on a thread while the device computes batch k. Returns per-step
        losses (one host sync per step — a faithful loss curve).

        Staleness bound: the producer runs up to its queue depth plus
        one in-flight pull ahead, and the push is deferred one step, so
        a key recurring within a 3-batch window trains on values up to
        THREE pushes stale — the async-PS trade-off (reference Async
        communicator semantics). Call step() directly for the
        one-step-stale synchronous profile."""
        # materialize ONCE: a generator would silently yield zero work
        # on every epoch after the first
        work = list(batches() if callable(batches) else batches)
        losses = []
        stop = threading.Event()
        for _ in range(int(epochs)):
            q: queue.Queue = queue.Queue(maxsize=2)

            def producer():
                for (keys, lod, feats, labels) in work:
                    if stop.is_set():
                        return
                    k = np.ascontiguousarray(keys, np.uint64).ravel()
                    with self._net_lock:
                        rows = self.client.pull_sparse(self.table, k,
                                                       self.emb_dim)
                    q.put((k, lod, feats, labels, rows))
                q.put(None)

            t = threading.Thread(target=producer, daemon=True)
            t.start()
            try:
                while True:
                    item = q.get()
                    if item is None:
                        break
                    k, lod, feats, labels, rows = item
                    losses.append(float(np.asarray(
                        self.step(k, lod, feats, labels, rows=rows))))
            except BaseException:
                # unblock the producer (it may be parked in q.put on the
                # full queue) so the thread and its pulled rows don't
                # outlive this call
                stop.set()
                while True:
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        break
                t.join(timeout=10.0)
                raise
            t.join()
            self.flush()
        return losses

    def dense_state(self):
        return dict(self._params)

    def write_back(self):
        """Copy the jitted step's dense params back onto the layer."""
        import jax
        lookup = dict(self.model.named_parameters())
        for k, v in self._params.items():
            if k in lookup:
                lookup[k]._data = jax.device_get(v)
