"""Dygraph data-parallel surface: init_parallel_env + DataParallel
(reference: python/paddle/distributed/parallel.py:57, DataParallel
fluid/dygraph/parallel.py:322 with the C++ bucketing Reducer
imperative/reducer.cc:376-748).

TPU-native: there is no reducer. In the jitted path DP is a batch
sharding and XLA fuses/schedules the grad all-reduces (what the
reference's bucket fusion + comm/compute overlap does by hand,
reducer.cc:685 FusedAllReduceSchedule). The eager path averages grads
across the 'dp' mesh axis after backward — correctness parity for
dygraph-style loops."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from . import collective, env as env_mod, mesh as mesh_mod

__all__ = ["init_parallel_env", "ParallelEnv", "DataParallel"]


class ParallelEnv:
    """Env-derived rank info (reference ParallelEnv dygraph/parallel.py)."""

    @property
    def rank(self):
        return env_mod.get_rank()

    @property
    def world_size(self):
        return env_mod.get_world_size()

    @property
    def device_id(self):
        return 0

    local_rank = rank
    nranks = world_size


def init_parallel_env():
    """Bootstrap multi-process jax + a default all-device 'dp' mesh."""
    env_mod.init_distributed()
    if mesh_mod.get_mesh() is None:
        mesh_mod.set_mesh(mesh_mod.build_mesh())
    return ParallelEnv()


class DataParallel:
    """Layer wrapper with DDP's API (forward passthrough, grad averaging).

    After `loss.backward()`, call `apply_collective_grads()` (the reference
    does this implicitly from C++ hooks; an explicit call keeps the eager
    tape simple) — it all-reduce-averages every trainable grad over 'dp'.
    Under jit (hapi / fleet compiled steps) this wrapper is transparent:
    sharded data already implies the reduction."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        self._layers = layers
        self._group = group
        self.find_unused_parameters = find_unused_parameters

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def apply_collective_grads(self):
        if jax.process_count() > 1:
            # multi-process eager DDP: per-process grads differ (different
            # data), so average across processes explicitly — the mesh-based
            # eager path would see replicated arrays and no-op
            from jax.experimental import multihost_utils
            for p in self._layers.parameters():
                if p.grad is not None:
                    stacked = multihost_utils.process_allgather(p.grad._data)
                    p.grad.set_value(stacked.mean(axis=0))
            return
        n = collective.get_group(
            self._group.axis if self._group else "dp").nranks
        if n <= 1:
            return
        for p in self._layers.parameters():
            if p.grad is not None:
                collective.all_reduce(p.grad, op=collective.ReduceOp.AVG,
                                      group=self._group)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)
