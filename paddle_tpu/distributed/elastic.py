"""Failure recovery: gang-restart supervision + checkpoint-resume loop.

Reference state of the art (SURVEY.md §5): no elastic training — the
launcher watches children and aborts (launch_utils.py:526
watch_local_trainers), PS mode has a HeartBeatMonitor. The TPU-native
equivalent: JAX's multi-controller runtime restarts the WHOLE job on any
worker loss, so recovery = supervisor (gang restart, bounded retries) +
sharded checkpoint resume (io/checkpoint.py). Two layers:

* supervise() — launcher-level: run the whole trainer gang, restart it
  from scratch up to max_restarts times when any rank fails. Trainers
  are expected to resume from their newest checkpoint on startup.

* run_with_recovery() — in-process: drive a step function with periodic
  checkpoints; on a transient failure, reload the newest VALID
  checkpoint and continue. A checkpoint that fails manifest validation
  or restore (torn write, corrupt shard) is skipped with a warning and
  the next older one is tried — a worker loss degrades to a one-step
  rollback, never a corrupt-state resume (docs/fault_tolerance.md).
"""
from __future__ import annotations

import os
import time
import warnings
from typing import Callable, Optional

from ..testing import chaos
from ..utils.retry import backoff_delays
from ..io.checkpoint import (CheckpointError, gc_checkpoints,
                             latest_checkpoint as _latest_valid,
                             list_checkpoints, validate_checkpoint)

__all__ = ["supervise", "run_with_recovery", "latest_checkpoint"]


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Newest step-numbered checkpoint directory under ckpt_dir
    (save_checkpoint targets named `step_{n}`) whose manifest validates
    — a half-written or corrupt step is skipped, never selected."""
    return _latest_valid(ckpt_dir)


def supervise(start_gang: Callable[[], list], max_restarts: int = 3,
              poll_s: float = 1.0, backoff_s: float = 5.0) -> int:
    """Launcher-level gang supervision: `start_gang()` launches the
    trainer processes (e.g. a start_local_trainers closure); any nonzero
    exit tears the gang down and relaunches it, up to max_restarts.
    Restarts back off exponentially (base `backoff_s`, jittered) so a
    crash-looping gang doesn't hammer the rendezvous store. Returns 0 on
    success; raises after exhausting restarts."""
    from .launch import watch_local_trainers

    delays = backoff_delays(max_restarts, base_delay=backoff_s,
                            max_delay=8 * backoff_s)
    attempt = 0
    while True:
        procs = start_gang()
        try:
            return watch_local_trainers(procs, poll_s=poll_s)
        except RuntimeError as e:
            attempt += 1
            if attempt > max_restarts:
                raise RuntimeError(
                    f"gang failed {attempt} times; giving up") from e
            time.sleep(next(delays))


def _restore_newest_valid(restore_fn, ckpt_dir):
    """Try checkpoints newest-first; one that fails validation or whose
    restore raises is skipped (warned), falling back to the previous
    step. Raises CheckpointError when nothing loads."""
    last_err = None
    for step, path in list_checkpoints(ckpt_dir):
        try:
            validate_checkpoint(path)
            return restore_fn(path)
        except Exception as e:          # noqa: BLE001 - any load fault
            last_err = e
            warnings.warn(f"checkpoint {path} unusable ({e}); "
                          "falling back to previous step")
    raise CheckpointError(
        f"no loadable checkpoint under {ckpt_dir}") from last_err


def run_with_recovery(step_fn: Callable[[int], None],
                      save_fn: Callable[[str, int], None],
                      restore_fn: Callable[[str], int],
                      ckpt_dir: str, total_steps: int,
                      checkpoint_every: int = 100,
                      max_restarts: int = 3,
                      keep_last: int = None,
                      backoff_s: float = 0.1,
                      max_backoff_s: float = 5.0):
    """Checkpointed training loop with transient-failure recovery.

    step_fn(step)            one training step
    save_fn(path, step)      write a checkpoint (CompiledTrainStep.
                             save_checkpoint fits directly)
    restore_fn(path) -> int  load a checkpoint, return its step
    On an exception from step_fn (or a failed save) the newest VALID
    checkpoint is restored — falling back past torn/corrupt steps — and
    the loop continues from there, up to max_restarts times with
    jittered exponential backoff between attempts. `keep_last=k` prunes
    older checkpoints after each successful save."""
    os.makedirs(ckpt_dir, exist_ok=True)
    step = 0
    if latest_checkpoint(ckpt_dir) is not None:
        step = _restore_newest_valid(restore_fn, ckpt_dir)
    else:
        # initial snapshot: a failure before the first periodic checkpoint
        # must restore pristine state, not replay onto mutated params
        save_fn(os.path.join(ckpt_dir, "step_0"), 0)
    restarts = 0
    delays = backoff_delays(max_restarts, base_delay=backoff_s,
                            max_delay=max_backoff_s)
    while step < total_steps:
        try:
            chaos.maybe_fail("step.fn", f"step={step}")
            step_fn(step)
            step += 1
            if step % checkpoint_every == 0 or step == total_steps:
                save_fn(os.path.join(ckpt_dir, f"step_{step}"), step)
                if keep_last:
                    gc_checkpoints(ckpt_dir, keep_last)
        except Exception:
            restarts += 1
            if restarts > max_restarts:
                raise
            time.sleep(next(delays))
            step = _restore_newest_valid(restore_fn, ckpt_dir)
    return step
