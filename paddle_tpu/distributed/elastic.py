"""Failure recovery: gang-restart supervision + checkpoint-resume loop.

Reference state of the art (SURVEY.md §5): no elastic training — the
launcher watches children and aborts (launch_utils.py:526
watch_local_trainers), PS mode has a HeartBeatMonitor. The TPU-native
equivalent: JAX's multi-controller runtime restarts the WHOLE job on any
worker loss, so recovery = supervisor (gang restart, bounded retries) +
sharded checkpoint resume (io/checkpoint.py). Two layers:

* supervise() — launcher-level: run the whole trainer gang, restart it
  from scratch up to max_restarts times when any rank fails. Trainers
  are expected to resume from their newest checkpoint on startup.

* run_with_recovery() — in-process: drive a step function with periodic
  checkpoints; on a transient failure, reload the newest checkpoint and
  continue. Useful for single-process training and as the body of each
  supervised trainer.
"""
from __future__ import annotations

import os
import time
from typing import Callable, Optional

__all__ = ["supervise", "run_with_recovery", "latest_checkpoint"]


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Newest step-numbered checkpoint directory under ckpt_dir
    (save_checkpoint targets named `step_{n}`)."""
    if not os.path.isdir(ckpt_dir):
        return None
    best, best_step = None, -1
    for name in os.listdir(ckpt_dir):
        if name.startswith("step_"):
            try:
                s = int(name.split("_", 1)[1])
            except ValueError:
                continue
            if s > best_step and os.path.exists(
                    os.path.join(ckpt_dir, name, "meta.json")):
                best, best_step = os.path.join(ckpt_dir, name), s
    return best


def supervise(start_gang: Callable[[], list], max_restarts: int = 3,
              poll_s: float = 1.0, backoff_s: float = 5.0) -> int:
    """Launcher-level gang supervision: `start_gang()` launches the
    trainer processes (e.g. a start_local_trainers closure); any nonzero
    exit tears the gang down and relaunches it, up to max_restarts.
    Returns 0 on success; raises after exhausting restarts."""
    from .launch import watch_local_trainers

    attempt = 0
    while True:
        procs = start_gang()
        try:
            return watch_local_trainers(procs, poll_s=poll_s)
        except RuntimeError as e:
            attempt += 1
            if attempt > max_restarts:
                raise RuntimeError(
                    f"gang failed {attempt} times; giving up") from e
            time.sleep(backoff_s)


def run_with_recovery(step_fn: Callable[[int], None],
                      save_fn: Callable[[str, int], None],
                      restore_fn: Callable[[str], int],
                      ckpt_dir: str, total_steps: int,
                      checkpoint_every: int = 100,
                      max_restarts: int = 3):
    """Checkpointed training loop with transient-failure recovery.

    step_fn(step)            one training step
    save_fn(path, step)      write a checkpoint (CompiledTrainStep.
                             save_checkpoint fits directly)
    restore_fn(path) -> int  load a checkpoint, return its step
    On an exception from step_fn the newest checkpoint is restored and
    the loop continues from there, up to max_restarts times."""
    os.makedirs(ckpt_dir, exist_ok=True)
    step = 0
    ck = latest_checkpoint(ckpt_dir)
    if ck is not None:
        step = restore_fn(ck)
    else:
        # initial snapshot: a failure before the first periodic checkpoint
        # must restore pristine state, not replay onto mutated params
        save_fn(os.path.join(ckpt_dir, "step_0"), 0)
    restarts = 0
    while step < total_steps:
        try:
            step_fn(step)
            step += 1
            if step % checkpoint_every == 0 or step == total_steps:
                save_fn(os.path.join(ckpt_dir, f"step_{step}"), step)
        except Exception:
            restarts += 1
            if restarts > max_restarts:
                raise
            step = restore_fn(latest_checkpoint(ckpt_dir))
    return step
