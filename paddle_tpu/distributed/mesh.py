"""Device-mesh construction — the TPU-native analog of the reference's
multi-device graph build (ir/multi_devices_graph_pass) + NCCL ring setup
(platform/collective_helper.h:67 NCCLCommContext keyed by ring_id).

A Mesh axis here == a comm ring there: 'dp' is the data-parallel allreduce
ring, 'tp' the tensor-parallel ring, 'pp' pipeline stages, 'sp' sequence
shards, 'ep' experts. XLA derives the collectives from shardings laid out
over these axes; no comm-init ops, no streams.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def build_mesh(shape: Optional[dict] = None,
               axis_names: Sequence[str] = ("dp",),
               devices=None) -> Mesh:
    """Build a Mesh from {axis: size}. Sizes of -1 are inferred.

    build_mesh({'dp': 2, 'tp': 4}) on 8 devices → 2x4 mesh.
    build_mesh() → all devices on one 'dp' axis.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = {axis_names[0]: n} if len(axis_names) == 1 else None
    if shape is None:
        raise ValueError("shape required for multi-axis mesh")
    names = list(shape.keys())
    sizes = list(shape.values())
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        sizes[sizes.index(-1)] = n // known
    if math.prod(sizes) != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} != {n} devices")
    dev_array = np.array(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))


_current_mesh = [None]


def set_mesh(mesh: Mesh):
    _current_mesh[0] = mesh


def get_mesh() -> Optional[Mesh]:
    return _current_mesh[0]


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))
