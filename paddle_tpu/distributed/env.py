"""Process-group environment (reference: ParallelEnv in
python/paddle/fluid/dygraph/parallel.py + PADDLE_TRAINER_* env protocol in
fleet/launch_utils.py).

On TPU the multi-host runtime is jax.distributed (the gen_comm_id_helper
analog): one process per host, all chips visible collectively. Environment
variables keep the reference names so launch scripts port over.
"""
from __future__ import annotations

import os

import jax

_initialized = False


def get_rank() -> int:
    """Global process rank (PADDLE_TRAINER_ID analog)."""
    if _initialized or "PADDLE_TRAINER_ID" in os.environ:
        return int(os.environ.get("PADDLE_TRAINER_ID", jax.process_index()))
    return 0


def get_world_size() -> int:
    """Number of processes (PADDLE_TRAINERS_NUM analog)."""
    if _initialized or "PADDLE_TRAINERS_NUM" in os.environ:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", jax.process_count()))
    return 1


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None):
    """jax.distributed.initialize wrapper — the TCP comm-id bootstrap analog
    (gen_comm_id_helper.cc:286 SendBroadCastCommID)."""
    global _initialized
    if _initialized:
        return
    addr = coordinator_address or os.environ.get("PADDLE_MASTER_ENDPOINT")
    if addr is None:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        addr = eps.split(",")[0] if eps else None
    n = num_processes or int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = process_id if process_id is not None else \
        int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if addr and n > 1:
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=n, process_id=pid)
    _initialized = True


class ParallelEnv:
    """reference: fluid/dygraph/parallel.py ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_LOCAL_RANK", "0"))

    @property
    def dev_id(self):
        return self.local_rank

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def nranks(self):
        return self.world_size
