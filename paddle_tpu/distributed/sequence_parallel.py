"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has NO long-context parallelism (SURVEY.md §5: repo-wide
grep negative — long sequences were handled by recompute + AMP only), so
these are new TPU-native components, not ports. They shard the SEQUENCE
dim of attention across the 'sp' mesh axis so context length scales with
chip count:

* ring_attention — K/V blocks rotate around the ICI ring (lax.ppermute)
  while Q stays resident; softmax is accumulated online (flash-style
  m/l/acc state) so no rank ever materialises full-T scores. Peak
  activation per chip: O(T/sp * T/sp) per step. Causal blocks strictly
  above the diagonal are computed-but-masked (they cost one matmul but
  keep the schedule static; a pl.when-style skip is a future optimisation).

* ulysses_attention — all-to-all re-shards the LOCAL heads: [B, T/sp,
  Hl, D] -> [B, T, Hl/sp, D] (heads split, sequence gathered), runs
  ordinary attention per head group (routing to the Pallas flash kernel
  at qualifying shapes), and all-to-alls back. Needs the local head
  count (H, or H/tp under head_axis sharding) divisible by sp; comm is
  2 all-to-alls instead of sp ppermutes, usually the winner on ICI while
  heads are plentiful.

Both run inside jax.shard_map over the 'sp' axis and compose with dp
(batch dim) and tensor-parallel head sharding (head_axis — attention is
per-head). Layouts follow the framework's [B, T, H, D] sdpa convention.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "ulysses_attention",
           "make_ring_attention", "make_ulysses_attention"]


def _block_attn_state(q, k, v, scale, m, l, acc, q_off, kv_off, causal):
    """One online-softmax accumulation step of q against a K/V block.
    q [B,Tq,H,D]; k,v [B,Tk,H,D]; m,l [B,H,Tq]; acc [B,Tq,H,D]."""
    qt = jnp.swapaxes(q, 1, 2)                     # [B,H,Tq,D]
    kt = jnp.swapaxes(k, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    s = s.astype(jnp.float32)
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        qpos = q_off + jnp.arange(Tq)[:, None]
        kpos = kv_off + jnp.arange(Tk)[None, :]
        s = jnp.where(qpos >= kpos, s, jnp.float32(-1e30))
    m_new = jnp.maximum(m, s.max(axis=-1))         # [B,H,Tq]
    p = jnp.exp(s - m_new[..., None])              # [B,H,Tq,Tk]
    corr = jnp.exp(m - m_new)                      # [B,H,Tq]
    l_new = l * corr + p.sum(axis=-1)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p, vt)      # [B,H,Tq,D]
    # acc layout [B,Tq,H,D]: bring corr to [B,Tq,H,1]
    corr_b = jnp.transpose(corr, (0, 2, 1))[..., None]
    acc_new = acc * corr_b + jnp.transpose(pv, (0, 2, 1, 3))
    return m_new, l_new, acc_new


def ring_attention(q, k, v, axis: str = "sp", causal: bool = False,
                   scale=None):
    """Shard_map-INNER ring attention: q/k/v are the local [B, T/sp, H, D]
    shards; returns the local output shard. Call inside shard_map/pjit
    over `axis` (or use make_ring_attention for the wrapped version)."""
    B, Tl, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    n = jax.lax.axis_size(axis)
    r = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]

    m0 = jnp.full((B, H, Tl), -1e30, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    acc0 = jnp.zeros((B, Tl, H, D), jnp.float32)

    def step(carry, t):
        kb, vb, m, l, acc = carry
        j = (r - t) % n                  # which global block we now hold
        m, l, acc = _block_attn_state(
            q, kb, vb, scale, m, l, acc,
            q_off=r * Tl, kv_off=j * Tl, causal=causal)
        kb = jax.lax.ppermute(kb, axis, perm)
        vb = jax.lax.ppermute(vb, axis, perm)
        return (kb, vb, m, l, acc), None

    (_, _, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n))
    denom = jnp.transpose(jnp.maximum(l, 1e-30), (0, 2, 1))[..., None]
    return (acc / denom).astype(q.dtype)


def ulysses_attention(q, k, v, axis: str = "sp", causal: bool = False,
                      scale=None):
    """Shard_map-INNER Ulysses: local [B, T/sp, H, D] -> all_to_all to
    [B, T, H/sp, D] -> full attention -> all_to_all back."""
    n = jax.lax.axis_size(axis)
    del n  # head split count == axis size; all_to_all handles it

    def a2a(x, split, concat):
        return jax.lax.all_to_all(x, axis, split_axis=split,
                                  concat_axis=concat, tiled=True)

    qg = a2a(q, 2, 1)   # [B, T, H/sp, D]
    kg = a2a(k, 2, 1)
    vg = a2a(v, 2, 1)

    D = q.shape[-1]
    T = qg.shape[1]
    s = scale if scale is not None else 1.0 / math.sqrt(D)

    # flash kernel on the gathered shard when it qualifies (same routing
    # rule as sdpa), else the XLA composition — O(T) memory either way on
    # TPU; the composition materialises [B, H/sp, T, T] and is the CPU/
    # small-shape fallback
    from ..core.flags import get_flags as _gf
    use_flash = (jax.default_backend() == "tpu"
                 and _gf("use_pallas_attention")
                 and T % 128 == 0
                 and T >= _gf("pallas_attention_min_seq"))
    if use_flash:
        from ..ops.pallas.flash_attention import flash_attention
        out = flash_attention(qg, kg, vg, causal=causal, scale=s)
    else:
        qt = jnp.swapaxes(qg, 1, 2)
        kt = jnp.swapaxes(kg, 1, 2)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * s
        logits = logits.astype(jnp.float32)
        if causal:
            mask = jnp.tril(jnp.ones((T, T), bool))
            logits = jnp.where(mask, logits, jnp.float32(-1e30))
        probs = jax.nn.softmax(logits, axis=-1).astype(qg.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, jnp.swapaxes(vg, 1, 2))
        out = jnp.swapaxes(out, 1, 2)   # [B, T, H/sp, D]
    return a2a(out, 1, 2)               # back to [B, T/sp, H, D]


def make_ring_attention(mesh, axis: str = "sp", causal: bool = False,
                        scale=None, batch_axis: str = None,
                        head_axis: str = None):
    """Jit-level wrapper: global [B, T, H, D] arrays, seq dim sharded over
    `axis` inside one shard_map (optionally batch over `batch_axis` and
    heads over `head_axis` — attention is per-head, so tensor-parallel
    head sharding composes with the ring for a dp x tp x sp mesh)."""
    dspec = P(batch_axis, axis, head_axis, None)

    fn = functools.partial(ring_attention, axis=axis, causal=causal,
                           scale=scale)
    return jax.shard_map(fn, mesh=mesh, in_specs=(dspec, dspec, dspec),
                         out_specs=dspec, check_vma=False)


def make_ulysses_attention(mesh, axis: str = "sp", causal: bool = False,
                           scale=None, batch_axis: str = None,
                           head_axis: str = None):
    dspec = P(batch_axis, axis, head_axis, None)
    fn = functools.partial(ulysses_attention, axis=axis, causal=causal,
                           scale=scale)
    return jax.shard_map(fn, mesh=mesh, in_specs=(dspec, dspec, dspec),
                         out_specs=dspec, check_vma=False)
