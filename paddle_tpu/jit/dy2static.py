"""paddle.jit.dy2static — the conversion-pass module under its reference
import path (python/paddle/jit/dy2static; the transformer stack). The
implementation is ast_transform; this module makes
`import paddle.jit.dy2static` port unchanged."""
from .ast_transform import (convert_function, convert_target,  # noqa: F401
                            enable_translation, maybe_convert,
                            translation_enabled)

# reference transformer-stack submodules (jit/dy2static/
# {convert_operators,convert_call_func,variable_trans_func}.py): the
# runtime combinators (__jst_cond/__jst_while/_jst_range + the scope
# machinery) all live in ast_transform; the names alias it
from . import ast_transform as convert_operators  # noqa: E402,F401
from . import ast_transform as convert_call_func  # noqa: E402,F401
from . import ast_transform as variable_trans_func  # noqa: E402,F401
