"""Depth-invariant-compilation support: persistent XLA compile cache,
explicit AOT warmup, and a retrace guard.

Three small pieces shared by the hapi single-device train step and the
fleet ``CompiledTrainStep`` (SPMD / pipeline / explicit-DP shard_map — all
strategy paths funnel through ``CompiledTrainStep.step``):

* ``setup_compilation_cache()`` points ``jax_compilation_cache_dir`` at
  ``PADDLE_TPU_COMPILE_CACHE`` (default ``~/.cache/paddle_tpu/xla``) so a
  recompile of an identical HLO module is a disk read, not an XLA run.
  Set the env var to ``0``/``off`` to disable.
* ``aot_compile(jitted, *args)`` replaces the first-step implicit compile
  with an explicit ``.lower().compile()``, timed and reported through
  ``paddle_tpu.profiler.record_compile`` with a cache hit/miss verdict
  (detected by diffing the cache directory around the compile).
* ``RetraceGuard`` fingerprints the (shape, dtype, sharding) signature of
  the step inputs; a mid-run change emits ONE structured warning naming
  the input that changed instead of silently recompiling.
  ``PADDLE_TPU_RETRACE=error`` escalates to ``RetraceError`` for CI;
  ``=off`` silences the warning (the recompile still happens).
"""
from __future__ import annotations

import os
import time
import warnings
from typing import Any, Dict, Optional, Tuple

from ..core import flags as _flags

__all__ = ["setup_compilation_cache", "suspend_compilation_cache",
           "cache_dir", "aot_compile", "AotCache",
           "RetraceGuard", "RetraceError", "RetraceWarning"]

_DISABLED = ("", "0", "off", "none", "disabled", "false")

# last directory applied to jax.config (setup is idempotent per dir)
_configured: list = [None]


def cache_dir() -> Optional[str]:
    """Resolved persistent-cache directory, or None when disabled."""
    d = _flags.env_raw("PADDLE_TPU_COMPILE_CACHE")
    if d is None:
        d = os.path.join("~", ".cache", "paddle_tpu", "xla")
    if d.strip().lower() in _DISABLED:
        return None
    return os.path.expanduser(d)


def setup_compilation_cache() -> Optional[str]:
    """Idempotently wire jax's persistent compilation cache.

    Returns the active cache directory, or None when disabled or when the
    jax build does not support the persistent cache (never raises — a
    missing cache only costs compile time)."""
    d = cache_dir()
    if d is None or _configured[0] == d:
        return _configured[0]
    try:
        import jax

        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        # the in-process cache object is created lazily on the FIRST
        # compile — which usually happened (disabled) during framework
        # import; reset so the new dir actually takes effect
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc)
            _cc.reset_cache()
        except Exception:
            pass
        # Default thresholds skip "cheap" (sub-second / small) compiles —
        # exactly the CPU-test regime; cache everything instead.
        for opt, val in (("jax_persistent_cache_min_compile_time_secs", 0),
                         ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(opt, val)
            except Exception:
                pass
    except Exception:
        return None
    _configured[0] = d
    return d


def _cache_listing(d: Optional[str]) -> Optional[set]:
    if d is None:
        return None
    try:
        return set(os.listdir(d))
    except OSError:
        return None


def suspend_compilation_cache() -> None:
    """Detach the persistent cache (until the next
    ``setup_compilation_cache`` call). Used for compiles that must not be
    served from disk — deserializing a multi-device executable on the CPU
    backend corrupts the heap (observed with forced-host-device meshes),
    so those compiles opt out via ``aot_compile(use_cache=False)``."""
    if _configured[0] is None:
        return
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", None)
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc)
            _cc.reset_cache()
        except Exception:
            pass
    except Exception:
        return
    _configured[0] = None


def aot_compile(jitted, *args, label: str = "step", use_cache: bool = True,
                **kwargs) -> Tuple[Any, Dict[str, Any]]:
    """Explicit ``jitted.lower(*args).compile()`` with timing + cache stats.

    Returns ``(compiled_executable, stats)`` where stats holds ``label``,
    ``compile_s`` and ``cache`` ("hit" | "miss" | "off"). The executable
    must be called directly (lowering does NOT seed the jit wrapper's own
    in-memory cache). Also records the compile via
    ``paddle_tpu.profiler.record_compile`` so bench/tools can report it.
    ``use_cache=False`` detaches the persistent cache for this compile
    (see :func:`suspend_compilation_cache`)."""
    if use_cache:
        d = setup_compilation_cache()
    else:
        suspend_compilation_cache()
        d = None
    before = _cache_listing(d)
    t0 = time.perf_counter()
    compiled = jitted.lower(*args, **kwargs).compile()
    dt = time.perf_counter() - t0
    if before is None:
        cache = "off"
    else:
        after = _cache_listing(d)
        cache = "miss" if after is None or (after - before) else "hit"
    stats = {"label": label, "compile_s": round(dt, 4), "cache": cache}
    from .. import profiler

    profiler.record_compile(label, dt, cache)
    from ..observability import tracez as _tracez

    _tracez.RING.complete(f"compile:{label}", t0, t0 + dt,
                          {"cache": cache})
    return compiled, stats


class _ProfiledExecutable:
    """The per-executable dispatch hook shared by tracez and profilez.

    Wraps one compiled executable: each call is timed twice — the call
    itself (JAX dispatches asynchronously, so this is host dispatch
    cost) and ``block_until_ready`` on the outputs (device execution).
    Both land in the tracez event ring (one "X" span per dispatch) and
    the profilez ``paddle_tpu_exec_*`` aggregates, keyed by the owning
    cache's label.  Every current AotCache call site reads the outputs
    on the host immediately after dispatching, so blocking here moves
    the wait, it does not add one.  A poisoned dispatch is NOT re-raised
    from the hook — it surfaces at the caller's read with its original
    traceback, exactly as without the wrapper.
    """

    __slots__ = ("_exe", "_label", "_donate")

    def __init__(self, exe, label: str, donate_argnums: Tuple[int, ...]):
        self._exe = exe
        self._label = label
        self._donate = donate_argnums

    def __getattr__(self, name):      # cost_analysis() etc. pass through
        return getattr(self._exe, name)

    def __call__(self, *args):
        donated = 0
        for i in self._donate:
            if i < len(args):
                donated += int(getattr(args[i], "nbytes", 0) or 0)
        t0 = time.perf_counter()
        out = self._exe(*args)
        t1 = time.perf_counter()
        try:
            import jax

            jax.block_until_ready(out)
        except Exception:
            pass                       # deferred failure: caller's read
        t2 = time.perf_counter()
        from ..observability import profilez as _profilez
        from ..observability import tracez as _tracez

        _tracez.RING.complete(f"exec:{self._label}", t0, t2)
        _profilez.PROFILER.observe(self._label, t1 - t0, t2 - t1, donated)
        return out


class AotCache:
    """Keyed cache of AOT-compiled executables — the serving bucket ladder's
    compile boundary.

    One executable per input-shape signature; a miss goes through
    :func:`aot_compile` (and is therefore recorded via
    ``profiler.record_compile``), a hit is a dict lookup with no jax
    dispatch-cache probe at all. The no-new-compiles-after-warmup property
    the serving engine asserts is exactly "every steady-state key is
    already in this dict". Thread-safe; a per-key pending event gives
    concurrent batch workers once-semantics (no duplicated XLA run)
    while the compile itself happens *outside* the map lock, so a cold
    bucket compiling never blocks hits on warmed buckets (tsan-lite
    flagged the old compile-under-lock hold as TPR102).

    Cached executables are returned wrapped in
    :class:`_ProfiledExecutable`, so every dispatch feeds the tracez
    event ring and the profilez per-executable aggregates for free."""

    def __init__(self, jitted, label: str = "aot",
                 donate_argnums: Tuple[int, ...] = ()):
        import threading

        self._jitted = jitted
        self._label = label
        # mirror of the jit's donate_argnums, used only to account
        # donated input bytes per dispatch (paddle_tpu_exec_donated_bytes)
        self._donate = tuple(donate_argnums or ())
        self._cache: Dict[tuple, Any] = {}
        self._lock = threading.Lock()
        self._pending: Dict[tuple, Any] = {}  # key -> threading.Event

    @staticmethod
    def signature(arrays) -> tuple:
        """Hashable (shape, dtype) signature of a positional arg list.
        Works on concrete arrays and ShapeDtypeStructs alike."""
        return tuple((tuple(a.shape), str(a.dtype)) for a in arrays)

    def get(self, key: tuple):
        with self._lock:
            return self._cache.get(key)

    def get_or_compile(self, *args, key: Optional[tuple] = None):
        """Return the executable for ``key`` (default: the signature of
        ``args``), compiling via ``jitted.lower(*args).compile()`` on a
        miss. ``args`` may mix concrete arrays (runtime miss) and
        ShapeDtypeStructs (warmup)."""
        import threading

        if key is None:
            key = self.signature(args)
        while True:
            with self._lock:
                exe = self._cache.get(key)
                if exe is not None:
                    return exe
                event = self._pending.get(key)
                if event is None:
                    event = self._pending[key] = threading.Event()
                    mine = True
                else:
                    mine = False
            if mine:
                try:
                    exe, stats = aot_compile(self._jitted, *args,
                                             label=f"{self._label}:{key}")
                    if stats:   # tests stub aot_compile with stats=None
                        from ..observability import profilez as _profilez

                        _profilez.PROFILER.record_compile(
                            self._label, stats["compile_s"])
                    exe = _ProfiledExecutable(exe, self._label,
                                              self._donate)
                    with self._lock:
                        self._cache[key] = exe
                    return exe
                finally:
                    with self._lock:
                        self._pending.pop(key, None)
                    event.set()
            # Another worker is compiling this key: wait for it, then
            # re-check (it may have failed — the loop retries the compile).
            event.wait(60.0)

    def keys(self):
        with self._lock:
            return list(self._cache)

    def __len__(self):
        with self._lock:
            return len(self._cache)


# ---------------------------------------------------------------------------
# retrace guard
# ---------------------------------------------------------------------------

class RetraceError(RuntimeError):
    """Raised on a mid-run input-signature change under
    ``PADDLE_TPU_RETRACE=error``."""


class RetraceWarning(UserWarning):
    """A compiled train step was handed inputs with a new signature."""


def _leaf_sig(leaf) -> tuple:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    sharding = getattr(leaf, "sharding", None)
    if shape is None:  # python static arg: fingerprint by value
        return ("static", repr(leaf))
    return (tuple(shape), str(dtype),
            None if sharding is None else str(sharding))


def _fingerprint(named_trees: Dict[str, Any]) -> Dict[str, tuple]:
    import jax

    fp = {}
    for group, tree in named_trees.items():
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        fp[group] = (str(treedef), tuple(_leaf_sig(l) for l in leaves))
    return fp


def _describe_diff(old: Dict[str, tuple], new: Dict[str, tuple]) -> str:
    import jax

    parts = []
    for group in new:
        o, n = old.get(group), new[group]
        if o == n:
            continue
        if o is None:
            parts.append(f"{group}: new input group")
            continue
        if o[0] != n[0]:
            parts.append(f"{group}: pytree structure changed")
            continue
        for i, (a, b) in enumerate(zip(o[1], n[1])):
            if a != b:
                parts.append(f"{group}[leaf {i}]: {a} -> {b}")
    for group in old:
        if group not in new:
            parts.append(f"{group}: input group removed")
    return "; ".join(parts) or "signature changed"


class RetraceGuard:
    """Per-compiled-step input-signature watchdog.

    ``check(**named_trees)`` returns ``"first"`` on the initial call,
    ``"match"`` while the signature is stable, and ``"retrace"`` when it
    changed — after emitting one :class:`RetraceWarning` naming the
    changed input (or raising :class:`RetraceError` when
    ``PADDLE_TPU_RETRACE=error``)."""

    def __init__(self, label: str = "step"):
        self.label = label
        self._fp: Optional[Dict[str, tuple]] = None
        self._warned = False

    def reset(self):
        self._fp = None
        self._warned = False

    def check(self, **named_trees) -> str:
        fp = _fingerprint(named_trees)
        if self._fp is None:
            self._fp = fp
            return "first"
        if fp == self._fp:
            return "match"
        diff = _describe_diff(self._fp, fp)
        mode = str(_flags.env_value("PADDLE_TPU_RETRACE")).strip().lower()
        msg = (f"paddle_tpu retrace guard [{self.label}]: compiled-step "
               f"input signature changed mid-run -> recompiling. "
               f"Changed: {diff}. (PADDLE_TPU_RETRACE=error makes this "
               f"fatal; =off silences it)")
        if mode == "error":
            raise RetraceError(msg)
        if mode != "off" and not self._warned:
            warnings.warn(msg, RetraceWarning, stacklevel=3)
            self._warned = True  # one structured warning per run
        self._fp = fp
        return "retrace"
