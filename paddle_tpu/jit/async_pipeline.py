"""Asynchronous step pipeline: bounded in-flight dispatch + deferred fetch.

JAX dispatches device computations asynchronously: calling the compiled
train step returns futures immediately, and the host only stalls when it
*reads* a value (``jax.device_get`` / ``block_until_ready``).  A train
loop that fetches the loss every step therefore serializes host collate,
dispatch and device compute — the chip idles for a full host round-trip
per step (on a remote-attached TPU that RTT dominates).  The fix is pure
reordering of host reads: keep the loss on device, keep up to N steps in
flight, and resolve metrics only at log/callback boundaries.  Numerics
are bit-identical to the synchronous loop — nothing about the computation
changes, only *when* the host looks at it.

Backpressure: an unbounded in-flight window lets the host race ahead of
the device, queueing batches (and their donated buffers) until the device
OOMs.  ``AsyncStepPipeline`` bounds the window (default 2, env
``PADDLE_TPU_ASYNC_STEPS``) by calling ``jax.block_until_ready`` on the
*oldest* ticket before admitting a new one; the blocked wall-clock is
accounted as ``host_blocked_s`` — on an overlapped pipeline it should be
a small fraction of total step time.

Error semantics: with async dispatch a poisoned batch (runtime error in
the compiled step) surfaces at the *fetch* boundary, not the dispatch
site.  Tickets capture the originating step index and re-raise as
``AsyncStepError(step_index=...)`` so the failing step is identifiable.
"""
from __future__ import annotations

import time
from typing import Any, List, Optional

from ..core import flags as _flags

__all__ = [
    "AsyncStepError",
    "AsyncStepPipeline",
    "StepTicket",
    "async_steps",
    "DEFAULT_ASYNC_STEPS",
]

DEFAULT_ASYNC_STEPS = 2


def async_steps(default: int = DEFAULT_ASYNC_STEPS) -> int:
    """In-flight window from ``PADDLE_TPU_ASYNC_STEPS``.

    ``0`` (or ``off``/``sync``) disables async stepping — the train loop
    fetches the loss synchronously every step.  ``>=1`` is the maximum
    number of dispatched-but-unfetched steps."""
    raw = (_flags.env_raw("PADDLE_TPU_ASYNC_STEPS") or "").strip().lower()
    if raw in ("off", "sync", "false", "no"):
        return 0
    try:
        n = int(raw) if raw else int(default)
    except ValueError:
        return int(default)
    return max(n, 0)


class AsyncStepError(RuntimeError):
    """A dispatched step failed; raised at the fetch boundary.

    ``step_index`` is the loop index of the originating dispatch (the
    poisoned batch), which by the time the error surfaces is typically
    several steps behind the loop counter."""

    def __init__(self, step_index: int, cause: BaseException):
        super().__init__(
            f"async train step {step_index} failed at the fetch boundary "
            f"(dispatched {type(cause).__name__}: {cause}); the offending "
            f"batch is step {step_index}, not the step being dispatched "
            f"when this raised")
        self.step_index = step_index
        self.__cause__ = cause


class StepTicket:
    """Handle for one dispatched step: on-device value(s) + timestamps."""

    __slots__ = ("step_index", "value", "submit_t", "ready_t",
                 "collate_s", "dispatch_s", "fetch_s", "_blocked")

    def __init__(self, step_index: int, value: Any,
                 collate_s: float = 0.0, dispatch_s: float = 0.0):
        self.step_index = step_index
        self.value = value
        self.submit_t = time.perf_counter()
        self.ready_t: Optional[float] = None
        self.collate_s = collate_s
        self.dispatch_s = dispatch_s
        self.fetch_s = 0.0
        self._blocked = False

    @property
    def done(self) -> bool:
        return self._blocked

    def block(self) -> float:
        """Wait until the device value is ready; returns seconds blocked.

        Re-raises any deferred step failure as :class:`AsyncStepError`
        carrying this ticket's step index."""
        if self._blocked:
            return 0.0
        t0 = time.perf_counter()
        try:
            # _AsyncScalar keeps its device loss in ._arr (None once it
            # has been fetched); plain arrays / pytrees block directly
            arr = getattr(self.value, "_arr", self.value)
            if arr is not None:
                import jax
                jax.block_until_ready(arr)
        except AsyncStepError:
            raise
        except Exception as e:  # noqa: BLE001 — deferred device failure
            self._blocked = True
            self.ready_t = time.perf_counter()
            raise AsyncStepError(self.step_index, e) from e
        self._blocked = True
        self.ready_t = time.perf_counter()
        self.fetch_s = self.ready_t - t0
        return self.fetch_s


class AsyncStepPipeline:
    """Bounded window of in-flight step tickets.

    ``submit()`` after each dispatch; when the window is full the call
    blocks on the *oldest* ticket (FIFO backpressure).  ``drain()`` at
    epoch end / loop exit retires everything, so deferred errors cannot
    escape the fit call that dispatched them.
    """

    def __init__(self, max_in_flight: Optional[int] = None,
                 label: str = "train", record: bool = True):
        self.max_in_flight = (async_steps() if max_in_flight is None
                              else max(int(max_in_flight), 1))
        self.label = label
        self.record = record
        self._inflight: List[StepTicket] = []
        self.host_blocked_s = 0.0
        self.steps_in_flight = 0      # max concurrently in flight
        self.steps_submitted = 0
        # stall flight recorder (PADDLE_TPU_STALL_DUMP): dumps thread
        # stacks + the in-flight window when steps stop retiring — a
        # device hang shows up here as "busy, no heartbeat"
        from ..observability import FlightRecorder
        from ..observability import tracez as _tracez
        self._recorder = FlightRecorder(
            f"async_steps_{label}",
            busy_fn=lambda: bool(self._inflight),
            context_fn=self._stall_context)
        self._ring = _tracez.RING

    def _stall_context(self):
        now = time.perf_counter()
        return {
            "label": self.label,
            "window": self.max_in_flight,
            "steps_submitted": self.steps_submitted,
            "in_flight": [{"step_index": t.step_index,
                           "age_s": round(now - t.submit_t, 3)}
                          for t in list(self._inflight)],
        }

    def submit(self, value: Any, step_index: int,
               collate_s: float = 0.0, dispatch_s: float = 0.0) -> StepTicket:
        t = StepTicket(step_index, value, collate_s, dispatch_s)
        self._inflight.append(t)
        self.steps_submitted += 1
        # dispatch span ends at submit: collate + dispatch led up to it
        self._ring.complete(
            f"step.dispatch:{self.label}",
            t.submit_t - collate_s - dispatch_s, t.submit_t,
            {"step": step_index})
        self._recorder.beat()
        while len(self._inflight) > self.max_in_flight:
            self._retire(self._inflight[0])
        # high-water mark AFTER backpressure: what was actually left in
        # flight, never the transient submit overshoot
        self.steps_in_flight = max(self.steps_in_flight, len(self._inflight))
        return t

    def drain(self) -> None:
        """Block on every outstanding ticket (oldest first)."""
        while self._inflight:
            self._retire(self._inflight[0])

    def close(self) -> None:
        """Stop the stall watchdog (idempotent; drain() first if the
        window may still hold tickets)."""
        self._recorder.stop()

    def _retire(self, t: StepTicket) -> None:
        try:
            blocked = t.block()
        finally:
            try:
                self._inflight.remove(t)
            except ValueError:
                pass
            self._recorder.beat()
        self.host_blocked_s += blocked
        if t.ready_t is not None:
            self._ring.complete(f"step.block:{self.label}",
                                t.ready_t - blocked, t.ready_t,
                                {"step": t.step_index})
        if self.record:
            from .. import profiler
            profiler.record_step(
                t.step_index,
                collate_s=t.collate_s,
                dispatch_s=t.dispatch_s,
                compute_s=max((t.ready_t or t.submit_t) - t.submit_t, 0.0),
                fetch_s=blocked,
                in_flight=min(self.steps_in_flight, self.max_in_flight),
                label=self.label)

    def stats(self) -> dict:
        return {
            "steps_in_flight": self.steps_in_flight,
            "host_blocked_s": round(self.host_blocked_s, 6),
            "steps_submitted": self.steps_submitted,
            "window": self.max_in_flight,
        }
