"""paddle.jit — dygraph-to-static + program save/load, TPU-native.

Reference surface: @to_static / @declarative (fluid/dygraph/jit.py:160,
dygraph_to_static/program_translator.py:756 — an AST transformer that
rewrites Python into ProgramDesc) and jit.save/jit.load +
save_inference_model (fluid/io.py:1199) which bundle a serialized program
with parameters so inference needs no model class.

TPU-native redesign: tracing is the main translation — `to_static` wraps
the layer in functional_call + jax.jit — plus a small AST pass
(ast_transform.py, the analog of dygraph_to_static's transformer stack)
that rewrites tensor-dependent plain-Python if/while into the static.nn
combinators so they lower to lax.cond/lax.while_loop instead of failing
the trace.
`save` exports the traced forward as a versioned StableHLO module
(jax.export) next to a parameter pickle; `load` rebuilds a callable
TranslatedLayer from those two artifacts alone — the NaiveExecutor-style
serve path (naive_executor.h analog): deserialize + bind params + run.

Artifacts (paddle naming parity):
    {path}.pdmodel    — serialized StableHLO module (jax.export bytes)
    {path}.pdiparams  — pickled {name: numpy} parameter payloads
"""
from __future__ import annotations

import functools
import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from ..core.tensor import Tensor
from ..framework import (functional_call, param_arrays, state_arrays,
                         unwrap as _untensor)
from ..static import InputSpec

__all__ = ["to_static", "save", "load", "TranslatedLayer", "not_to_static",
           "ProgramTranslator", "TracedLayer", "set_code_level",
           "set_verbosity", "dy2static"]

# the conversion-pass module under its reference name (python/paddle/
# jit/__init__.py imports `from . import dy2static`)
from . import dy2static  # noqa: E402


def _spec_to_aval(spec, sym_ctx):
    """InputSpec -> ShapeDtypeStruct with export symbols for dynamic dims.

    Sharing rules (multi-input models need equal dynamic dims to share ONE
    symbol or tracing fails on shape mismatch): a None LEADING dim is the
    shared 'batch' symbol across all inputs; a string dim (e.g.
    shape=[None, "seqlen"]) shares the symbol of that name; None elsewhere
    gets a fresh independent symbol."""
    def sym(name):
        if name not in sym_ctx:
            sym_ctx[name] = jax_export.symbolic_shape(
                name, scope=sym_ctx["scope"])[0]
        return sym_ctx[name]

    dims = []
    for i, d in enumerate(spec.shape):
        if isinstance(d, str):
            dims.append(sym(d))
        elif d is None or (isinstance(d, int) and d < 0):
            dims.append(sym("batch" if i == 0 else f"d{i}_{id(spec)}"))
        else:
            dims.append(int(d))
    return jax.ShapeDtypeStruct(tuple(dims), spec.dtype)


class StaticFunction:
    """What @to_static returns: the layer/function with a jit-compiled
    functional fast path and enough metadata for jit.save."""

    def __init__(self, fn_or_layer, input_spec=None):
        self._input_spec = input_spec
        self._is_layer = hasattr(fn_or_layer, "named_parameters")
        self._jit_cache = {}
        # AST pass (reference program_translator.py:756): rewrite
        # tensor-dependent plain-Python if/while in the forward into the
        # static.nn combinators so un-annotated models trace and export
        from .ast_transform import convert_target
        self._target = convert_target(fn_or_layer)
        functools.update_wrapper(self, getattr(
            fn_or_layer, "forward", fn_or_layer), updated=())

    def _jitted_for(self, static_kwargs):
        """One compiled entry per static-kwarg combination (non-array
        kwargs like training=False are compile-time constants)."""
        key = tuple(sorted(static_kwargs.items()))
        if key not in self._jit_cache:
            if self._is_layer:
                def _run(p, st, *args):
                    out, _ = functional_call(self._target, p, st, *args,
                                             mutable_state=False,
                                             **dict(key))
                    return out
            else:
                def _run(*args):
                    # a converted body's static.nn combinators return
                    # Tensor objects — unwrap before leaving jax.jit
                    # (Tensor is not a valid JAX output type)
                    return _untensor(self._target(*args, **dict(key)))
            self._jit_cache[key] = jax.jit(_run)
        return self._jit_cache[key]

    def __call__(self, *args, **kwargs):
        from .ast_transform import translation_enabled
        if not translation_enabled():
            # ProgramTranslator.enable(False): run dygraph per the
            # reference contract (decided per CALL, not at decoration)
            return self._target(*args, **kwargs)
        arrayish = (Tensor, jnp.ndarray, np.ndarray)
        static_kw = {k: v for k, v in kwargs.items()
                     if not isinstance(v, arrayish)}
        if len(static_kw) != len(kwargs):
            raise NotImplementedError(
                "to_static: tensor-valued keyword arguments are not "
                "supported; pass tensors positionally")
        raw = [a._data if isinstance(a, Tensor) else a for a in args]
        if not self._is_layer:
            out = self._jitted_for(static_kw)(*raw)
            return jax.tree_util.tree_map(Tensor, out)
        p = param_arrays(self._target)
        st = state_arrays(self._target)
        out = self._jitted_for(static_kw)(p, st, *raw)
        return jax.tree_util.tree_map(Tensor, out)

    # paddle parity helpers
    @property
    def inner_layer(self):
        return self._target if self._is_layer else None

    def concrete_program(self, *specs):  # reference: partial_program
        return self._jitted_for({})


def to_static(function=None, input_spec=None, **kwargs):
    """Decorator/wrapper: paddle.jit.to_static(layer_or_fn).

    The engine is trace-and-compile (jax.jit over functional_call),
    with the ast_transform pass rewriting tensor-dependent plain-Python
    if/while into lax-lowering combinators first (transformed frames
    show `<to_static ...>` filenames in tracebacks)."""
    if function is None:
        return lambda f: to_static(f, input_spec=input_spec, **kwargs)
    return StaticFunction(function, input_spec)


def not_to_static(func):
    """Parity marker (reference jit.py not_to_static): excluded from
    translation — a no-op here since tracing follows real calls."""
    return func


def save(layer, path, input_spec=None):
    """Serialize `layer`'s forward as StableHLO + params; the result loads
    and runs with jit.load without the model class (reference:
    save_inference_model fluid/io.py:1199 + jit.save)."""
    target = layer._target if isinstance(layer, StaticFunction) else layer
    spec = input_spec or getattr(layer, "_input_spec", None)
    if spec is None:
        raise ValueError("jit.save needs input_spec=[InputSpec(...), ...] "
                         "to trace the exported program")
    is_layer = hasattr(target, "named_parameters")
    # AST pass (see StaticFunction): un-annotated tensor-dependent
    # if/while must lower to lax for the export trace. For layers the
    # converted forward is swapped in only for the trace — save must not
    # permanently mutate the caller's object.
    from .ast_transform import maybe_convert
    restore_forward = None
    did_swap = False
    if is_layer:
        conv = maybe_convert(target.forward)
        if getattr(conv, "__jst_converted__", False) and not \
                getattr(target.forward, "__jst_converted__", False):
            restore_forward = target.__dict__.get("forward", None)
            target.forward = conv
            did_swap = True
    else:
        target = maybe_convert(target)
    was_training = bool(getattr(target, "training", False))
    if hasattr(target, "eval"):
        target.eval()            # export inference behavior (no dropout)
    try:
        if is_layer:
            params = param_arrays(target)
            state = state_arrays(target)
            merged = {**params, **state}

            def fwd(pp, *inputs):
                out, _ = functional_call(target, pp, {}, *inputs,
                                         mutable_state=False)
                return out
        else:
            merged = {}          # plain function: no parameters to bundle

            def fwd(pp, *inputs):
                del pp
                return _untensor(target(*inputs))

        sym_ctx = {"scope": jax_export.SymbolicScope()}
        in_avals = tuple(
            _spec_to_aval(s if isinstance(s, InputSpec) else InputSpec(*s),
                          sym_ctx)
            for s in spec)
        p_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                   for k, v in merged.items()}
        exported = jax_export.export(jax.jit(fwd))(p_avals, *in_avals)
    finally:
        if was_training and hasattr(target, "train"):
            target.train()
        if is_layer and did_swap:
            # undo the temporary converted-forward swap (and ONLY then —
            # a pre-existing instance forward must survive save)
            if restore_forward is not None:
                target.forward = restore_forward
            else:
                target.__dict__.pop("forward", None)

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({k: np.asarray(jax.device_get(v))
                     for k, v in merged.items()}, f, protocol=4)


class TranslatedLayer:
    """Loaded inference program: deserialized StableHLO + bound params —
    runnable without the original model class (reference TranslatedLayer
    fluid/dygraph/io.py; executor analog: NaiveExecutor)."""

    def __init__(self, exported, params):
        self._exported = exported
        self._params = params
        self._call = jax.jit(exported.call)

    def __call__(self, *args):
        raw = [a._data if isinstance(a, Tensor) else jnp.asarray(a)
               for a in args]
        out = self._call(self._params, *raw)
        return jax.tree_util.tree_map(Tensor, out)

    def forward(self, *args):
        return self(*args)

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is an inference program; "
                           "training state was not exported")

    @property
    def program_bytes(self):
        return self._exported.serialize()

    @property
    def in_avals(self):
        return self._exported.in_avals

    @property
    def out_avals(self):
        """Flat output avals — the exported program's output arity is
        known before the first call (inference.Predictor derives
        get_output_names from this)."""
        return self._exported.out_avals

    @property
    def input_avals(self):
        """Avals of the USER inputs only. jax flattens the export args
        ``(params_dict, *inputs)`` dict-leaves-first, so the trailing
        ``len(in_avals) - len(params)`` entries are the positional
        inputs; their symbolic dims mark the dynamic axes the serving
        bucket ladder pads."""
        return self._exported.in_avals[len(self._params):]


def load(path, params_path=None):
    """jit.load: read {path}.pdmodel + params -> TranslatedLayer.
    params default to {path}.pdiparams; pass params_path to load them from
    elsewhere (the two-file inference.Config form)."""
    model_file = path if path.endswith(".pdmodel") else path + ".pdmodel"
    with open(model_file, "rb") as f:
        exported = jax_export.deserialize(f.read())
    params_file = params_path or (
        model_file[:-len(".pdmodel")] + ".pdiparams")
    with open(params_file, "rb") as f:
        params = {k: jnp.asarray(v) for k, v in pickle.load(f).items()}
    return TranslatedLayer(exported, params)


class ProgramTranslator:
    """Singleton controlling dygraph-to-static translation (reference
    dygraph_to_static/program_translator.py ProgramTranslator): enable()
    toggles the AST conversion pass globally."""

    _instance = None

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static):
        from .ast_transform import enable_translation
        enable_translation(enable_to_static)

    @property
    def enable_to_static(self):
        from .ast_transform import translation_enabled
        return translation_enabled()


def set_verbosity(level=0, also_to_stdout=False):
    """Translation logging level (reference jit.set_verbosity)."""
    from . import ast_transform as _at
    _at._VERBOSITY[0] = int(level)


def set_code_level(level=100, also_to_stdout=False):
    """Print converted sources as they are produced, up to `level`
    conversions per function (reference jit.set_code_level)."""
    from . import ast_transform as _at
    _at._CODE_LEVEL[0] = int(level)


class TracedLayer:
    """Trace-and-bundle a layer from example inputs (reference
    fluid/dygraph/jit.py TracedLayer): trace() runs the layer, returns
    (traced, outputs); the traced object calls through jit and
    save_inference_model exports the jit artifacts."""

    def __init__(self, layer, inputs):
        self._static = StaticFunction(layer)
        self._layer = layer
        self._inputs = inputs

    @classmethod
    def trace(cls, layer, inputs):
        """Returns (dygraph_outputs, traced_layer) — the reference's
        order (fluid/dygraph/jit.py TracedLayer.trace)."""
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        tl = cls(layer, inputs)
        outs = tl(*inputs)
        return outs, tl

    def __call__(self, *args):
        return self._static(*args)

    def save_inference_model(self, path, feed=None, fetch=None, **kw):
        specs = [InputSpec(list(np.asarray(
            a._data if isinstance(a, Tensor) else a).shape),
            str(np.asarray(a._data if isinstance(a, Tensor)
                           else a).dtype)) for a in self._inputs]
        save(self._layer, path, input_spec=specs)
        return path
