"""AST-level dygraph->static conversion for plain-Python control flow.

Reference: dygraph_to_static/program_translator.py:756 + the transformer
stack (ifelse_transformer.py, loop_transformer.py) — the reference
rewrites `if`/`while` on tensor values into cond/while_loop ops at the
source level so un-annotated user code traces into a Program.

TPU-native version: the same source rewrite, but the targets are the
static.nn combinators (which resolve eagerly on concrete values and
lower to lax.cond / lax.while_loop under tracing):

    if x.mean() > 0:        ->   def __jst_true():  y = a; return (y,)
        y = a                    def __jst_false(): y = b; return (y,)
    else:                        (y,) = __jst_cond(x.mean() > 0,
        y = b                                      __jst_true, __jst_false)

    while n.sum() < k:      ->   def __jst_cond0(n): return n.sum() < k
        n = n + 1                def __jst_body0(n): n = n + 1; return (n,)
                                 [n] = __jst_while(__jst_cond0,
                                                   __jst_body0, [n])

Supported shapes: assignment-style if/else (no return/break/continue in
the branches), both-branches-single-return if/else, and assignment-style
while. Anything else is left as genuine Python with a one-time warning —
concrete values still run; tensor-dependent untransformed control flow
surfaces jax's tracer-bool error at trace time (the documented
fallback). Nested callees are not rewritten (convert them explicitly
with paddle.jit.to_static)."""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
import warnings

import numpy as np

__all__ = ["convert_function", "maybe_convert"]


def _tensorish(x):
    from ..core.tensor import Tensor
    import jax
    import jax.numpy as jnp
    return isinstance(x, (Tensor, jnp.ndarray, jax.core.Tracer))


_JST_UNDEF = object()     # call-site placeholder for not-yet-bound locals


def _jst_cond(pred, true_fn, false_fn, vals=()):
    """Runtime dispatch: python `if` for plain values, static.nn.cond
    (eager-resolving, lax-lowering) for tensor predicates. `vals` are the
    current values of the branch-state variables, passed as positional
    args so branch bodies may rebind them (a closure read of a rebound
    name would hit UnboundLocalError)."""
    tf = lambda: true_fn(*vals)     # noqa: E731
    ff = lambda: false_fn(*vals)    # noqa: E731
    if not _tensorish(pred):
        return tf() if pred else ff()
    from ..static import nn as snn
    return snn.cond(pred, tf, ff)


def _jst_while(cond_fn, body_fn, loop_vars):
    """Runtime dispatch for `while`: static.nn.while_loop handles both
    concrete (host loop) and traced (lax.while_loop) conditions; a plain
    python loop serves the no-tensor case exactly."""
    probe = cond_fn(*loop_vars)
    if not _tensorish(probe) and not any(_tensorish(v) for v in loop_vars):
        out = list(loop_vars)
        while cond_fn(*out):
            res = body_fn(*out)
            out = list(res) if isinstance(res, (list, tuple)) else [res]
        return out
    from ..static import nn as snn
    return snn.while_loop(cond_fn, body_fn, list(loop_vars))


def _assigned_names(stmts):
    """Names bound by a statement list (Assign/AugAssign/AnnAssign/For
    targets), in deterministic order."""
    found = []

    def add(n):
        if n not in found:
            found.append(n)

    for node in stmts:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                add(sub.id)
            elif isinstance(sub, ast.AugAssign) and isinstance(
                    sub.target, ast.Name):
                add(sub.target.id)
    return found


def _has_control_escape(stmts):
    for node in stmts:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Return, ast.Break, ast.Continue,
                                ast.Yield, ast.YieldFrom)):
                return True
    return False


def _names_loaded(node):
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _loaded_before_store(stmts):
    """Names with a loop-carried dependency: loaded before any store in
    a linear pass over the statement list (iteration-local temps —
    stored first, loaded later — are excluded). Within one statement the
    RHS evaluates before the target, which matches ast.walk's
    value-before-target field order for Assign."""
    stored = set()
    carried = []
    for node in stmts:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                if isinstance(sub.ctx, ast.Load):
                    if sub.id not in stored and sub.id not in carried:
                        carried.append(sub.id)
                elif isinstance(sub.ctx, ast.Store):
                    stored.add(sub.id)
            elif isinstance(sub, ast.AugAssign) and isinstance(
                    sub.target, ast.Name):
                # target is read-then-written
                if sub.target.id not in stored and \
                        sub.target.id not in carried:
                    carried.append(sub.target.id)
    return carried


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0
        self.changed = False
        self.skipped = False

    # -- if ---------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        body, orelse = node.body, node.orelse

        def single_return(stmts):
            return (len(stmts) == 1 and isinstance(stmts[0], ast.Return)
                    and stmts[0].value is not None)

        if single_return(body) and single_return(orelse):
            # return __jst_cond(test, lambda: e1, lambda: e2)
            lam_t = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=body[0].value)
            lam_f = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=orelse[0].value)
            self.changed = True
            call = ast.Call(
                func=ast.Name(id="__jst_cond", ctx=ast.Load()),
                args=[node.test, lam_t, lam_f], keywords=[])
            return ast.copy_location(ast.Return(value=call), node)

        if (_has_control_escape(body) or _has_control_escape(orelse)):
            self.skipped = True
            return node

        out = _assigned_names(body) + [
            n for n in _assigned_names(orelse)
            if n not in _assigned_names(body)]
        out = [n for n in out if not n.startswith("__jst")]
        i = self.counter
        self.counter += 1
        self.changed = True
        # branch fns take the state vars as PARAMETERS (a branch body
        # rebinding `h` makes `h` local — a closure read of the outer
        # value would raise UnboundLocalError); current values ride the
        # __jst_cond call, sentinel-filled for not-yet-bound names
        params = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=n) for n in out],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        ret_tuple = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in out] or
                 [ast.Constant(value=0)],
            ctx=ast.Load()))
        fn_t = ast.FunctionDef(
            name=f"__jst_true_{i}", args=params,
            body=list(body) + [ret_tuple], decorator_list=[])
        fn_f = ast.FunctionDef(
            name=f"__jst_false_{i}", args=params,
            body=(list(orelse) or [ast.Pass()]) + [ret_tuple],
            decorator_list=[])
        # __jst_v_n = n if bound else _JST_UNDEF  (per state var)
        grabs = []
        for n in out:
            grabs.append(ast.Try(
                body=[ast.Assign(
                    targets=[ast.Name(id=f"__jst_v_{n}", ctx=ast.Store())],
                    value=ast.Name(id=n, ctx=ast.Load()))],
                handlers=[ast.ExceptHandler(
                    type=ast.Tuple(
                        elts=[ast.Name(id="NameError", ctx=ast.Load()),
                              ast.Name(id="UnboundLocalError",
                                       ctx=ast.Load())],
                        ctx=ast.Load()),
                    name=None,
                    body=[ast.Assign(
                        targets=[ast.Name(id=f"__jst_v_{n}",
                                          ctx=ast.Store())],
                        value=ast.Name(id="__jst_undef",
                                       ctx=ast.Load()))])],
                orelse=[], finalbody=[]))
        call = ast.Call(
            func=ast.Name(id="__jst_cond", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=fn_t.name, ctx=ast.Load()),
                  ast.Name(id=fn_f.name, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=f"__jst_v_{n}",
                                           ctx=ast.Load()) for n in out],
                            ctx=ast.Load())],
            keywords=[])
        if out:
            assign = ast.Assign(
                targets=[ast.Tuple(
                    elts=[ast.Name(id=n, ctx=ast.Store()) for n in out],
                    ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        return [ast.copy_location(n_, node)
                for n_ in (fn_t, fn_f, *grabs, assign)]

    # -- while ------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has_control_escape(node.body):
            self.skipped = True
            return node
        # loop-carried vars only: assigned in the body AND read before
        # written (or read by the test). Iteration-local temps stay local
        # to the body fn — note the python loop-variable leak (reading a
        # body temp AFTER the loop) is not preserved.
        assigned = [n for n in _assigned_names(node.body)
                    if not n.startswith("__jst")]
        carried = set(_loaded_before_store(node.body)) | \
            _names_loaded(node.test)
        loop_vars = [n for n in assigned if n in carried]
        if not loop_vars:
            self.skipped = True
            return node
        i = self.counter
        self.counter += 1
        self.changed = True
        params = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=n) for n in loop_vars],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        fn_c = ast.FunctionDef(
            name=f"__jst_loopcond_{i}", args=params,
            body=[ast.Return(value=node.test)], decorator_list=[])
        fn_b = ast.FunctionDef(
            name=f"__jst_loopbody_{i}", args=params,
            body=list(node.body) + [ast.Return(value=ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Load()) for n in loop_vars],
                ctx=ast.Load()))],
            decorator_list=[])
        call = ast.Call(
            func=ast.Name(id="__jst_while", ctx=ast.Load()),
            args=[ast.Name(id=fn_c.name, ctx=ast.Load()),
                  ast.Name(id=fn_b.name, ctx=ast.Load()),
                  ast.List(elts=[ast.Name(id=n, ctx=ast.Load())
                                 for n in loop_vars], ctx=ast.Load())],
            keywords=[])
        assign = ast.Assign(
            targets=[ast.List(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in loop_vars],
                ctx=ast.Store())],
            value=call)
        return [ast.copy_location(n_, node) for n_ in (fn_c, fn_b, assign)]


def convert_function(fn):
    """Rewrite tensor-dependent if/while in `fn` into the static.nn
    combinators. Returns the converted function, or `fn` unchanged (with
    a warning) when the source can't be transformed."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError) as e:
        warnings.warn(
            f"to_static: cannot read source of {fn!r} ({e}); falling back "
            "to trace-time resolution — tensor-dependent Python `if`/"
            "`while` will fail under tracing")
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []     # the wrapper re-applies nothing

    tr = _ControlFlowTransformer()
    tr.visit(fdef)
    ast.fix_missing_locations(tree)
    if tr.skipped:
        warnings.warn(
            f"to_static: some control flow in {fn.__qualname__} uses "
            "return/break/continue inside if/while bodies and was left as "
            "plain Python (resolved at trace time; tensor-dependent "
            "predicates there will fail under tracing)")
    if not tr.changed:
        return fn                # nothing to do

    # closure variables become globals of the compiled copy
    namespace = dict(fn.__globals__)
    if fn.__closure__:
        namespace.update(zip(fn.__code__.co_freevars,
                             (c.cell_contents for c in fn.__closure__)))
    namespace["__jst_cond"] = _jst_cond
    namespace["__jst_while"] = _jst_while
    namespace["__jst_undef"] = _JST_UNDEF
    code = compile(tree, filename=f"<to_static {fn.__qualname__}>",
                   mode="exec")
    exec(code, namespace)
    converted = namespace[fdef.name]
    converted = functools.wraps(fn)(converted)
    converted.__jst_converted__ = True
    return converted


def maybe_convert(fn):
    """convert_function with idempotence (already-converted functions and
    bound methods pass through converted)."""
    if getattr(fn, "__jst_converted__", False):
        return fn
    if isinstance(fn, types.MethodType):
        conv = convert_function(fn.__func__)
        if conv is fn.__func__:
            return fn
        return types.MethodType(conv, fn.__self__)
    return convert_function(fn)


def convert_target(obj):
    """Apply the AST pass to a layer (rewriting its forward in place) or
    a plain function (returning the converted function) — the shared
    entry for StaticFunction and jit.save."""
    if hasattr(obj, "named_parameters"):
        conv = maybe_convert(obj.forward)
        if getattr(conv, "__jst_converted__", False) and not \
                getattr(obj.forward, "__jst_converted__", False):
            obj.forward = conv
        return obj
    return maybe_convert(obj)
