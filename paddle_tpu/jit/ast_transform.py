"""AST-level dygraph->static conversion for plain-Python control flow.

Reference: dygraph_to_static/program_translator.py:756 + the transformer
stack (ifelse_transformer.py, loop_transformer.py) — the reference
rewrites `if`/`while` on tensor values into cond/while_loop ops at the
source level so un-annotated user code traces into a Program.

TPU-native version: the same source rewrite, but the targets are the
static.nn combinators (which resolve eagerly on concrete values and
lower to lax.cond / lax.while_loop under tracing):

    if x.mean() > 0:        ->   def __jst_true():  y = a; return (y,)
        y = a                    def __jst_false(): y = b; return (y,)
    else:                        (y,) = __jst_cond(x.mean() > 0,
        y = b                                      __jst_true, __jst_false)

    while n.sum() < k:      ->   def __jst_cond0(n): return n.sum() < k
        n = n + 1                def __jst_body0(n): n = n + 1; return (n,)
                                 [n] = __jst_while(__jst_cond0,
                                                   __jst_body0, [n])

Supported shapes: assignment-style if/else (no return in the branches),
both-branches-single-return if/else, assignment-style while/for-range,
and `break`/`continue` inside those loops (eliminated Paddle-style into
boolean flag carries + guard `if`s before the loop lowering — the loop
test absorbs the break flag, statements after a flag-set point are
wrapped in `if not flag:`). Anything else is left as genuine Python with
a one-time warning —
concrete values still run; tensor-dependent untransformed control flow
surfaces jax's tracer-bool error at trace time (the documented
fallback). Nested callees are not rewritten (convert them explicitly
with paddle.jit.to_static)."""
from __future__ import annotations

import ast
import copy
import functools
import inspect
import textwrap
import types
import warnings

import numpy as np

__all__ = ["convert_function", "maybe_convert"]


def _tensorish(x):
    from ..core.tensor import Tensor
    import jax
    import jax.numpy as jnp
    return isinstance(x, (Tensor, jnp.ndarray, jax.core.Tracer))


_JST_UNDEF = object()     # call-site placeholder for not-yet-bound locals


def _jst_cond(pred, true_fn, false_fn, vals=(), risky=()):
    """Runtime dispatch: python `if` for plain values, static.nn.cond
    (eager-resolving, lax-lowering) for tensor predicates. `vals` are the
    current values of the branch-state variables, passed as positional
    args so branch bodies may rebind them (a closure read of a rebound
    name would hit UnboundLocalError). `risky` names vars assigned in
    only ONE branch: unbound-before + traced predicate means the other
    branch would emit the raw sentinel into lax.cond — refuse clearly."""
    tf = lambda: true_fn(*vals)     # noqa: E731
    ff = lambda: false_fn(*vals)    # noqa: E731
    if not _tensorish(pred):
        return tf() if pred else ff()
    undef = [n for n, v in risky if v is _JST_UNDEF]
    if undef:
        raise NotImplementedError(
            f"to_static: variable(s) {undef} are bound in only one branch "
            "of a tensor-dependent `if` — lax.cond needs both branches to "
            "produce every output; bind them before the `if`")
    from ..static import nn as snn
    return snn.cond(pred, tf, ff)


def _jst_while(cond_fn, body_fn, loop_vars, n_carried=None):
    """Runtime dispatch for `while`: static.nn.while_loop handles both
    concrete (host loop) and traced (lax.while_loop) conditions; a plain
    python loop serves the no-tensor case exactly.

    loop_vars[:n_carried] are true loop-carried names; the tail holds
    body-local temps (stored before loaded each iteration) that Python
    semantics leak out of the loop — they ride along so a read AFTER the
    loop sees the last iteration's value. Temps unbound before the loop
    arrive as the _JST_UNDEF sentinel; their input values are dead (the
    body writes them before any read), and the caller deletes any name
    still sentinel-valued after the loop so a later read raises NameError
    exactly as unconverted Python would."""
    if n_carried is None:
        n_carried = len(loop_vars)
    carried, extras = list(loop_vars[:n_carried]), list(loop_vars[n_carried:])
    probe = cond_fn(*loop_vars)
    if not _tensorish(probe) and not any(_tensorish(v) for v in carried):
        out = list(loop_vars)
        while cond_fn(*out):
            res = body_fn(*out)
            out = list(res) if isinstance(res, (list, tuple)) else [res]
        return out
    from ..static import nn as snn
    if not extras:
        return snn.while_loop(cond_fn, body_fn, carried)
    # Traced loop with body temps: lax.while_loop needs a typed initial
    # carry for every output. A temp's INPUT is dead (the body writes it
    # before any read), so one abstract body evaluation with scalar
    # placeholders yields the temps' output avals. A temp bound BEFORE
    # the loop seeds the carry with its real value (correct for zero and
    # >=1 iterations alike); an unbound one gets zeros — a dynamic trip
    # count cannot reproduce Python's NameError-only-when-zero-iterations
    # there. Temps that aren't array-typed (strings, lists) or whose
    # pre-loop binding has a different shape can't ride a traced carry —
    # fall back to carrying only the true loop vars and leave the temps
    # undefined after the loop (the caller's sentinel guard turns a later
    # read into NameError, with a warning here explaining why).
    import jax
    import jax.lax
    import jax.numpy as jnp
    from ..core.tensor import Tensor

    def _raw(x):
        return x._data if isinstance(x, Tensor) else x

    def _fallback(reason):
        warnings.warn(
            f"to_static: body-local temp(s) of a tensor-dependent `while` "
            f"cannot be carried through lax.while_loop ({reason}); they "
            "will be undefined after the loop")
        out_c = snn.while_loop(
            lambda *c: cond_fn(*c, *extras),
            lambda *c: list(body_fn(*c, *extras))[:n_carried], carried)
        return list(out_c) + [_JST_UNDEF] * len(extras)

    try:
        ph = [jnp.zeros(()) for _ in extras]
        out_avals = jax.eval_shape(
            lambda c, e: [_raw(r) for r in body_fn(*c, *e)[n_carried:]],
            tuple(_raw(v) for v in carried), tuple(ph))
    except Exception:
        return _fallback("not array-typed")

    extra_init = []
    for v, a in zip(extras, out_avals):
        if v is _JST_UNDEF:
            extra_init.append(jnp.zeros(a.shape, a.dtype))
        elif np.shape(_raw(v)) == tuple(a.shape):
            extra_init.append(jax.lax.convert_element_type(_raw(v),
                                                           a.dtype))
        else:
            return _fallback("pre-loop binding has a different shape "
                             "than the loop body produces")

    def body_strong(*vals):
        # pin the temps' dtypes: eval_shape may report weak types while
        # jnp.zeros seeds are strong — lax.while_loop requires the carry
        # types to match exactly across iterations
        res = list(body_fn(*vals))
        res[n_carried:] = [
            jax.lax.convert_element_type(_raw(r), a.dtype)
            for r, a in zip(res[n_carried:], out_avals)]
        return res

    return snn.while_loop(cond_fn, body_strong, carried + extra_init)


def _jst_unwrap(x):
    """Tensor -> raw jnp value (jnp.asarray on a Tensor wrapping a tracer
    would route through __array__ and die with TracerArrayConversionError)."""
    from ..core.tensor import Tensor
    return x._data if isinstance(x, Tensor) else x


def _jst_loop_ok(pred, brk):
    """Loop-continue test with a break flag folded in: `pred and not brk`,
    tensor-aware (break/continue elimination rewrites `while pred:` with a
    body `break` into `while __jst_loop_ok(pred, brk):`)."""
    if _tensorish(pred) or _tensorish(brk):
        import jax.numpy as jnp
        return jnp.logical_and(jnp.asarray(_jst_unwrap(pred)),
                               jnp.logical_not(jnp.asarray(_jst_unwrap(brk))))
    return bool(pred) and not bool(brk)


def _jst_not_any(*flags):
    """`not (f1 or f2 ...)` over break/continue flags, tensor-aware —
    the guard predicate wrapped around statements that follow a
    (possibly conditional) break/continue in the same body."""
    if any(_tensorish(f) for f in flags):
        import jax.numpy as jnp
        out = jnp.asarray(False)
        for f in flags:
            out = jnp.logical_or(out, jnp.asarray(_jst_unwrap(f)))
        return jnp.logical_not(out)
    return not any(bool(f) for f in flags)


def _jst_for_exit(i, brk, step):
    """Post-loop value of a for-range index under break elimination: a
    broken loop keeps the index where it stopped (the bump is guarded),
    a completed loop un-bumps the final increment — tensor-aware."""
    if _tensorish(brk) or _tensorish(i):
        import jax.numpy as jnp
        i, brk, step = (_jst_unwrap(v) for v in (i, brk, step))
        return jnp.where(jnp.asarray(brk), i, i - step)
    return i if brk else i - step


class _JstRange:
    """range(...) whose bounds hold tensors/tracers — the traced-for
    carrier (__jst_range returns a real `range` when all args are
    concrete, so this type's presence MEANS the trip count is
    data-dependent)."""

    def __init__(self, start, stop, step):
        self.start = start
        self.stop = stop
        self.step = step


def _jst_range(*args):
    if len(args) == 1:
        start, stop, step = 0, args[0], 1
    elif len(args) == 2:
        start, stop, step = args[0], args[1], 1
    elif len(args) == 3:
        start, stop, step = args
    else:
        raise TypeError(f"range expected 1-3 arguments, got {len(args)}")
    if not any(_tensorish(a) for a in (start, stop, step)):
        return range(int(start), int(stop), int(step))
    return _JstRange(start, stop, step)


def _jst_rng_cond(i, r):
    """Loop-continue predicate for a _JstRange index carry."""
    step = r.step
    if _tensorish(step):
        raise NotImplementedError(
            "to_static: tensor-valued range STEP is not supported "
            "(tensor start/stop are); make the step a python int")
    return (i < r.stop) if step > 0 else (i > r.stop)


_NESTED_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _walk_same_scope(node):
    """ast.walk that does NOT descend into nested function scopes (their
    returns/stores belong to the nested function, not this one)."""
    for child in ast.iter_child_nodes(node):
        yield child
        if not isinstance(child, _NESTED_SCOPES):
            yield from _walk_same_scope(child)


def _assigned_names(stmts):
    """Names bound by a statement list in THIS scope
    (Assign/AugAssign/AnnAssign/For targets), in deterministic order."""
    found = []

    def add(n):
        if n not in found:
            found.append(n)

    for node in stmts:
        if isinstance(node, _NESTED_SCOPES):
            continue             # a def/lambda statement binds no Name here
        for sub in [node] + list(_walk_same_scope(node)):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                add(sub.id)
            elif isinstance(sub, ast.AugAssign) and isinstance(
                    sub.target, ast.Name):
                add(sub.target.id)
    return found


def _has_control_escape(stmts):
    """Return/break/continue/yield in THIS scope (synthesized __jst_*
    inner functions and user lambdas don't count)."""
    for node in stmts:
        if isinstance(node, _NESTED_SCOPES):
            continue
        for sub in [node] + list(_walk_same_scope(node)):
            if isinstance(sub, (ast.Return, ast.Break, ast.Continue,
                                ast.Yield, ast.YieldFrom)):
                return True
    return False


def _has_return_or_yield(stmts):
    """Return/yield in THIS scope — the escapes break/continue
    elimination cannot absorb (they leave the function, not the loop)."""
    for node in stmts:
        if isinstance(node, _NESTED_SCOPES):
            continue
        for sub in [node] + list(_walk_same_scope(node)):
            if isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)):
                return True
    return False


_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While)


def _own_break_continue(stmts):
    """True when the statement list contains a break/continue belonging
    to the CURRENT loop — nested loops own their breaks, nested function
    scopes own everything."""
    for node in stmts:
        if isinstance(node, (ast.Break, ast.Continue)):
            return True
        if isinstance(node, _NESTED_SCOPES + _LOOP_NODES):
            continue
        for field in ("body", "orelse", "finalbody"):
            if _own_break_continue(getattr(node, field, None) or []):
                return True
        for h in getattr(node, "handlers", None) or []:
            if _own_break_continue(h.body):
                return True
    return False


class _BreakContinueRewriter:
    """Flag-based break/continue elimination for ONE loop body
    (reference: dygraph_to_static/break_continue_transformer.py — the
    same technique: each `break`/`continue` becomes a boolean-flag
    assignment, every statement after a flag-set point is wrapped in
    `if not flag:` guards, and the loop test absorbs the break flag).

    Flags are named `_jst_brk{i}` / `_jst_cont{i}` — single leading
    underscore on purpose: the `__jst` prefix is filtered OUT of the
    while-lowering's state-variable list, and the flags must ride the
    loop carry. Nested loops are left alone (their own visit pass
    handles their breaks)."""

    def __init__(self, idx):
        self.brk = f"_jst_brk{idx}"
        self.cont = f"_jst_cont{idx}"
        self.used_brk = False
        self.used_cont = False

    @staticmethod
    def _set(name):
        return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                          value=ast.Constant(value=True))

    @staticmethod
    def _reset(name):
        return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                          value=ast.Constant(value=False))

    def flags(self):
        out = []
        if self.used_brk:
            out.append(self.brk)
        if self.used_cont:
            out.append(self.cont)
        return out

    def inits(self):
        return [self._reset(f) for f in self.flags()]

    def rewrite_body(self, stmts):
        """Returns the loop body with break/continue eliminated; call
        `flags()`/`inits()` afterwards for the pre-loop flag bindings."""
        # pre-scan so every guard tests the full flag set, regardless of
        # where in the body the first flag-set statement sits
        self.used_brk = self._uses(stmts, ast.Break)
        self.used_cont = self._uses(stmts, ast.Continue)
        out = self._guard(stmts)
        if self.used_cont:
            # continue only skips the REST of this iteration
            out = [self._reset(self.cont)] + out
        return out

    @staticmethod
    def _uses(stmts, kind):
        for node in stmts:
            if isinstance(node, kind):
                return True
            if isinstance(node, _NESTED_SCOPES + _LOOP_NODES):
                continue
            for field in ("body", "orelse", "finalbody"):
                if _BreakContinueRewriter._uses(
                        getattr(node, field, None) or [], kind):
                    return True
            for h in getattr(node, "handlers", None) or []:
                if _BreakContinueRewriter._uses(h.body, kind):
                    return True
        return False

    def _sets_flag(self, stmt):
        return _own_break_continue([stmt])

    def _guard_test(self):
        return ast.Call(
            func=ast.Name(id="__jst_not_any", ctx=ast.Load()),
            args=[ast.Name(id=f, ctx=ast.Load()) for f in self.flags()],
            keywords=[])

    def _guard(self, stmts):
        """Rewrite one statement list: flag-set statements replace
        break/continue, and everything after the first statement that
        can set a flag is wrapped in `if __jst_not_any(flags):`."""
        out = []
        for i, s in enumerate(stmts):
            sets = self._sets_flag(s)
            out.append(self._rewrite(s))
            if sets:
                rest = stmts[i + 1:]
                if rest:
                    out.append(ast.If(test=self._guard_test(),
                                      body=self._guard(rest), orelse=[]))
                break
        return out

    def _rewrite(self, s):
        if isinstance(s, ast.Break):
            return self._set(self.brk)
        if isinstance(s, ast.Continue):
            return self._set(self.cont)
        if isinstance(s, _NESTED_SCOPES + _LOOP_NODES):
            return s               # nested loop/function: not our escape
        if isinstance(s, ast.If):
            return ast.copy_location(
                ast.If(test=s.test, body=self._guard(s.body),
                       orelse=self._guard(s.orelse) if s.orelse else []),
                s)
        if isinstance(s, ast.With):
            return ast.copy_location(
                ast.With(items=s.items, body=self._guard(s.body)), s)
        if isinstance(s, ast.Try):
            return ast.copy_location(
                ast.Try(body=self._guard(s.body),
                        handlers=[ast.ExceptHandler(
                            type=h.type, name=h.name,
                            body=self._guard(h.body))
                            for h in s.handlers],
                        orelse=self._guard(s.orelse) if s.orelse else [],
                        finalbody=s.finalbody), s)
        return s


def _names_loaded(node):
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _events(node):
    """Yield ('load'|'store', name) in EVALUATION order: Assign values
    before targets (ast.walk would visit targets first — its field order
    is (targets, value)), AugAssign targets as load-then-store. Nested
    function scopes contribute their free-variable loads at the def
    site and no stores."""
    if isinstance(node, _NESTED_SCOPES):
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                yield ("load", n.id)
        return
    if isinstance(node, ast.Assign):
        yield from _events(node.value)
        for t in node.targets:
            yield from _events(t)
        return
    if isinstance(node, ast.AnnAssign):
        if node.value is not None:
            yield from _events(node.value)
        yield from _events(node.target)
        return
    if isinstance(node, ast.AugAssign):
        if isinstance(node.target, ast.Name):
            yield ("load", node.target.id)
        yield from _events(node.value)
        if isinstance(node.target, ast.Name):
            yield ("store", node.target.id)
        else:
            yield from _events(node.target)
        return
    if isinstance(node, ast.Name):
        yield (("store" if isinstance(node.ctx, ast.Store) else "load"),
               node.id)
        return
    for child in ast.iter_child_nodes(node):
        yield from _events(child)


def _loaded_before_store(stmts):
    """Names with a loop-carried dependency: loaded before any store in
    evaluation order over the statement list (iteration-local temps —
    stored first, loaded later — are excluded)."""
    stored = set()
    carried = []
    for node in stmts:
        for kind, name in _events(node):
            if kind == "load":
                if name not in stored and name not in carried:
                    carried.append(name)
            else:
                stored.add(name)
    return carried


def _grab_or_undef(n):
    """`__jst_v_{n} = n` guarded by try/except -> sentinel when unbound."""
    return ast.Try(
        body=[ast.Assign(
            targets=[ast.Name(id=f"__jst_v_{n}", ctx=ast.Store())],
            value=ast.Name(id=n, ctx=ast.Load()))],
        handlers=[ast.ExceptHandler(
            type=ast.Tuple(
                elts=[ast.Name(id="NameError", ctx=ast.Load()),
                      ast.Name(id="UnboundLocalError", ctx=ast.Load())],
                ctx=ast.Load()),
            name=None,
            body=[ast.Assign(
                targets=[ast.Name(id=f"__jst_v_{n}", ctx=ast.Store())],
                value=ast.Name(id="__jst_undef", ctx=ast.Load()))])],
        orelse=[], finalbody=[])


def _undef_guard(n):
    """`if n is __jst_undef: del n` — a name the construct could not bind
    must end the statement unbound (NameError on a later read), not bound
    to the leaked sentinel object."""
    return ast.If(
        test=ast.Compare(
            left=ast.Name(id=n, ctx=ast.Load()),
            ops=[ast.Is()],
            comparators=[ast.Name(id="__jst_undef", ctx=ast.Load())]),
        body=[ast.Delete(targets=[ast.Name(id=n, ctx=ast.Del())])],
        orelse=[])


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0
        self.changed = False
        self.skipped = False

    # -- if ---------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        body, orelse = node.body, node.orelse

        def single_return(stmts):
            return (len(stmts) == 1 and isinstance(stmts[0], ast.Return)
                    and stmts[0].value is not None)

        if single_return(body) and single_return(orelse):
            # return __jst_cond(test, lambda: e1, lambda: e2)
            lam_t = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=body[0].value)
            lam_f = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=orelse[0].value)
            self.changed = True
            call = ast.Call(
                func=ast.Name(id="__jst_cond", ctx=ast.Load()),
                args=[node.test, lam_t, lam_f], keywords=[])
            return ast.copy_location(ast.Return(value=call), node)

        if (_has_control_escape(body) or _has_control_escape(orelse)):
            self.skipped = True
            return node

        out = _assigned_names(body) + [
            n for n in _assigned_names(orelse)
            if n not in _assigned_names(body)]
        out = [n for n in out if not n.startswith("__jst")]
        i = self.counter
        self.counter += 1
        self.changed = True
        # branch fns take the state vars as PARAMETERS (a branch body
        # rebinding `h` makes `h` local — a closure read of the outer
        # value would raise UnboundLocalError); current values ride the
        # __jst_cond call, sentinel-filled for not-yet-bound names
        params = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=n) for n in out],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        ret_tuple = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in out] or
                 [ast.Constant(value=0)],
            ctx=ast.Load()))
        fn_t = ast.FunctionDef(
            name=f"__jst_true_{i}", args=params,
            body=list(body) + [ret_tuple], decorator_list=[])
        fn_f = ast.FunctionDef(
            name=f"__jst_false_{i}", args=params,
            body=(list(orelse) or [ast.Pass()]) + [ret_tuple],
            decorator_list=[])
        # __jst_v_n = n if bound else _JST_UNDEF  (per state var)
        grabs = [_grab_or_undef(n) for n in out]
        in_both = set(_assigned_names(body)) & set(_assigned_names(orelse))
        risky = [n for n in out if n not in in_both]
        call = ast.Call(
            func=ast.Name(id="__jst_cond", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=fn_t.name, ctx=ast.Load()),
                  ast.Name(id=fn_f.name, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=f"__jst_v_{n}",
                                           ctx=ast.Load()) for n in out],
                            ctx=ast.Load()),
                  ast.Tuple(elts=[
                      ast.Tuple(elts=[ast.Constant(value=n),
                                      ast.Name(id=f"__jst_v_{n}",
                                               ctx=ast.Load())],
                                ctx=ast.Load())
                      for n in risky], ctx=ast.Load())],
            keywords=[])
        if out:
            assign = ast.Assign(
                targets=[ast.Tuple(
                    elts=[ast.Name(id=n, ctx=ast.Store()) for n in out],
                    ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        # a risky name whose branch did not run comes back as the sentinel
        # (concrete predicate + unbound-before): unbind it so a later read
        # raises NameError exactly as the untransformed Python would
        guards = [_undef_guard(n) for n in risky]
        return [ast.copy_location(n_, node)
                for n_ in (fn_t, fn_f, *grabs, assign, *guards)]

    # -- for --------------------------------------------------------------
    def visit_For(self, node):
        """`for <name> in range(...)` with a tensor-dependent bound
        lowers to the while machinery (reference loop_transformer.py:294
        visit_For). A CONCRETE range keeps the plain Python loop — it
        unrolls at trace time, which XLA prefers for short loops and
        which stays differentiable (lax.while_loop is not) — so the
        range()-vs-traced dispatch happens at RUNTIME via __jst_range:

            __jst_R = __jst_range(args...)
            if isinstance(__jst_R, range):   # concrete: native python
                for i in __jst_R: body
            else:                            # traced bound: lax path
                i = __jst_R.start
                [while-converted: cond __jst_rng_cond(i, R), body+step]

        Non-range iterables stay untouched: lists/tuples and tensors
        have static trip counts (a tensor's leading dim is a static
        shape), so plain Python iteration already traces correctly."""
        it = node.iter
        is_range_call = (isinstance(it, ast.Call)
                         and isinstance(it.func, ast.Name)
                         and it.func.id == "range" and not it.keywords)
        if not is_range_call or not isinstance(node.target, ast.Name):
            self.generic_visit(node)
            return node          # static-trip-count python loop: leave it
        if node.orelse or _has_return_or_yield(node.body):
            self.skipped = True
            self.generic_visit(node)
            return node
        if _own_break_continue(node.body):
            return self._for_with_break_continue(node)
        self.generic_visit(node)
        i = self.counter
        self.counter += 1
        rng = f"__jst_R_{i}"
        tgt = node.target.id
        setup = ast.Assign(
            targets=[ast.Name(id=rng, ctx=ast.Store())],
            value=ast.Call(func=ast.Name(id="__jst_range", ctx=ast.Load()),
                           args=list(it.args), keywords=[]))
        concrete_for = ast.For(
            target=ast.Name(id=tgt, ctx=ast.Store()),
            iter=ast.Name(id=rng, ctx=ast.Load()),
            body=copy.deepcopy(node.body), orelse=[])
        # traced branch: index-carried while over the range formula
        init = ast.Assign(
            targets=[ast.Name(id=tgt, ctx=ast.Store())],
            value=ast.Attribute(value=ast.Name(id=rng, ctx=ast.Load()),
                                attr="start", ctx=ast.Load()))
        bump = ast.Assign(
            targets=[ast.Name(id=tgt, ctx=ast.Store())],
            value=ast.BinOp(
                left=ast.Name(id=tgt, ctx=ast.Load()), op=ast.Add(),
                right=ast.Attribute(value=ast.Name(id=rng, ctx=ast.Load()),
                                    attr="step", ctx=ast.Load())))
        wh = ast.While(
            test=ast.Call(func=ast.Name(id="__jst_rng_cond",
                                        ctx=ast.Load()),
                          args=[ast.Name(id=tgt, ctx=ast.Load()),
                                ast.Name(id=rng, ctx=ast.Load())],
                          keywords=[]),
            body=list(node.body) + [bump], orelse=[])
        ast.copy_location(wh, node)
        ast.fix_missing_locations(wh)
        converted = self._build_while(wh)
        if converted is wh:      # while conversion declined
            self.skipped = True
            return node
        # python leaves the loop var at the LAST YIELDED index; the
        # while lowering bumps once more after the final iteration, so
        # undo one step (a zero-trip traced loop leaves start - step
        # where python leaves the name unbound — the same dynamic-trip
        # caveat as while body temps)
        unbump = ast.Assign(
            targets=[ast.Name(id=tgt, ctx=ast.Store())],
            value=ast.BinOp(
                left=ast.Name(id=tgt, ctx=ast.Load()), op=ast.Sub(),
                right=ast.Attribute(value=ast.Name(id=rng, ctx=ast.Load()),
                                    attr="step", ctx=ast.Load())))
        dispatch = ast.If(
            test=ast.Call(func=ast.Name(id="isinstance", ctx=ast.Load()),
                          args=[ast.Name(id=rng, ctx=ast.Load()),
                                ast.Name(id="range", ctx=ast.Load())],
                          keywords=[]),
            body=[concrete_for],
            orelse=[init] + list(converted) + [unbump])
        self.changed = True
        return [ast.copy_location(n_, node) for n_ in (setup, dispatch)]

    def _for_with_break_continue(self, node):
        """`for <name> in range(...)` containing break/continue: flag
        elimination + the while lowering for BOTH concrete and traced
        ranges (__jst_while's runtime dispatch runs concrete loops as a
        host loop, so native-for unrolling is the only thing given up).
        The index bump is guarded on the break flag (continue still
        advances, break freezes the index), and the post-loop un-bump
        becomes a select on the break flag (__jst_for_exit)."""
        i = self.counter
        self.counter += 1
        rng = f"__jst_R_{i}"
        tgt = node.target.id
        rw = _BreakContinueRewriter(i)
        body = rw.rewrite_body(copy.deepcopy(node.body))
        setup = ast.Assign(
            targets=[ast.Name(id=rng, ctx=ast.Store())],
            value=ast.Call(func=ast.Name(id="__jst_range", ctx=ast.Load()),
                           args=list(node.iter.args), keywords=[]))
        init = ast.Assign(
            targets=[ast.Name(id=tgt, ctx=ast.Store())],
            value=ast.Attribute(value=ast.Name(id=rng, ctx=ast.Load()),
                                attr="start", ctx=ast.Load()))
        step_of_rng = ast.Attribute(value=ast.Name(id=rng, ctx=ast.Load()),
                                    attr="step", ctx=ast.Load())
        bump = ast.Assign(
            targets=[ast.Name(id=tgt, ctx=ast.Store())],
            value=ast.BinOp(left=ast.Name(id=tgt, ctx=ast.Load()),
                            op=ast.Add(), right=step_of_rng))
        test = ast.Call(
            func=ast.Name(id="__jst_rng_cond", ctx=ast.Load()),
            args=[ast.Name(id=tgt, ctx=ast.Load()),
                  ast.Name(id=rng, ctx=ast.Load())],
            keywords=[])
        if rw.used_brk:
            bump = ast.If(
                test=ast.Call(
                    func=ast.Name(id="__jst_not_any", ctx=ast.Load()),
                    args=[ast.Name(id=rw.brk, ctx=ast.Load())],
                    keywords=[]),
                body=[bump], orelse=[])
            test = ast.Call(
                func=ast.Name(id="__jst_loop_ok", ctx=ast.Load()),
                args=[test, ast.Name(id=rw.brk, ctx=ast.Load())],
                keywords=[])
            exitfix = ast.Assign(
                targets=[ast.Name(id=tgt, ctx=ast.Store())],
                value=ast.Call(
                    func=ast.Name(id="__jst_for_exit", ctx=ast.Load()),
                    args=[ast.Name(id=tgt, ctx=ast.Load()),
                          ast.Name(id=rw.brk, ctx=ast.Load()),
                          step_of_rng],
                    keywords=[]))
        else:
            exitfix = ast.Assign(
                targets=[ast.Name(id=tgt, ctx=ast.Store())],
                value=ast.BinOp(left=ast.Name(id=tgt, ctx=ast.Load()),
                                op=ast.Sub(), right=step_of_rng))
        wh = ast.While(test=test, body=body + [bump], orelse=[])
        ast.copy_location(wh, node)
        ast.fix_missing_locations(wh)
        self.generic_visit(wh)   # convert inner ifs (incl. guard ifs)
        converted = self._build_while(wh)
        if converted is wh:      # while conversion declined
            self.skipped = True
            return node
        self.changed = True
        out = [setup, init] + rw.inits() + list(converted) + [exitfix]
        return [ast.copy_location(n_, node) for n_ in out]

    # -- while ------------------------------------------------------------
    def visit_While(self, node):
        node, inits = self._while_break_continue(node)
        self.generic_visit(node)
        built = self._build_while(node)
        if built is node:
            # conversion declined; the rewritten body is still faithful
            # plain Python (the flags emulate break/continue exactly)
            return inits + [node] if inits else node
        return inits + list(built) if inits else built

    def _while_break_continue(self, node):
        """Eliminate this while's own break/continue (flags + guards)
        BEFORE generic_visit so the synthesized flag-set and guard `if`s
        ride the normal __jst_cond transformation. Returns the (possibly
        rewritten) node plus the pre-loop flag initializers."""
        if node.orelse or not _own_break_continue(node.body) or \
                _has_return_or_yield(node.body):
            return node, []
        rw = _BreakContinueRewriter(self.counter)
        self.counter += 1
        body = rw.rewrite_body(list(node.body))
        test = node.test
        if rw.used_brk:
            test = ast.Call(
                func=ast.Name(id="__jst_loop_ok", ctx=ast.Load()),
                args=[node.test, ast.Name(id=rw.brk, ctx=ast.Load())],
                keywords=[])
        new = ast.While(test=test, body=body, orelse=[])
        ast.copy_location(new, node)
        ast.fix_missing_locations(new)
        self.changed = True
        return new, [ast.copy_location(s, node) for s in rw.inits()]

    def _build_while(self, node):
        if node.orelse or _has_control_escape(node.body):
            self.skipped = True
            return node
        # ALL body-assigned names ride the loop (python scoping leaks
        # them: a body temp read AFTER the loop sees the last iteration's
        # value). The true loop-carried ones — read before written, or
        # read by the test — come first; temps follow with sentinel-
        # filled initial values (__jst_while treats their inputs as dead)
        # and get a post-loop del-guard so a zero-iteration loop leaves
        # them unbound, as plain Python would.
        assigned = [n for n in _assigned_names(node.body)
                    if not n.startswith("__jst")]
        carried = set(_loaded_before_store(node.body)) | \
            _names_loaded(node.test)
        loop_vars = [n for n in assigned if n in carried]
        extras = [n for n in assigned if n not in carried]
        if not loop_vars:
            self.skipped = True
            return node
        i = self.counter
        self.counter += 1
        self.changed = True
        all_vars = loop_vars + extras
        params = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=n) for n in all_vars],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        fn_c = ast.FunctionDef(
            name=f"__jst_loopcond_{i}", args=params,
            body=[ast.Return(value=node.test)], decorator_list=[])
        fn_b = ast.FunctionDef(
            name=f"__jst_loopbody_{i}", args=params,
            body=list(node.body) + [ast.Return(value=ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Load()) for n in all_vars],
                ctx=ast.Load()))],
            decorator_list=[])
        grabs = [_grab_or_undef(n) for n in extras]
        call = ast.Call(
            func=ast.Name(id="__jst_while", ctx=ast.Load()),
            args=[ast.Name(id=fn_c.name, ctx=ast.Load()),
                  ast.Name(id=fn_b.name, ctx=ast.Load()),
                  ast.List(
                      elts=[ast.Name(id=n, ctx=ast.Load())
                            for n in loop_vars] +
                           [ast.Name(id=f"__jst_v_{n}", ctx=ast.Load())
                            for n in extras],
                      ctx=ast.Load()),
                  ast.Constant(value=len(loop_vars))],
            keywords=[])
        assign = ast.Assign(
            targets=[ast.List(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in all_vars],
                ctx=ast.Store())],
            value=call)
        guards = [_undef_guard(n) for n in extras]
        return [ast.copy_location(n_, node)
                for n_ in (fn_c, fn_b, *grabs, assign, *guards)]


def _decorator_tail(dec):
    """Final attribute name of a decorator expression: `paddle.jit.
    to_static`, `jit.to_static(...)` and bare `to_static` all ->
    'to_static'; anything unrecognisable -> None."""
    t = dec.func if isinstance(dec, ast.Call) else dec
    if isinstance(t, ast.Attribute):
        return t.attr
    if isinstance(t, ast.Name):
        return t.id
    return None


def convert_function(fn):
    """Rewrite tensor-dependent if/while in `fn` into the static.nn
    combinators. Returns the converted function, or `fn` unchanged (with
    a warning) when the source can't be transformed."""
    if not _TRANSLATION_ENABLED[0]:
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError) as e:
        warnings.warn(
            f"to_static: cannot read source of {fn!r} ({e}); falling back "
            "to trace-time resolution — tensor-dependent Python `if`/"
            "`while` will fail under tracing")
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    # `@paddle.jit.to_static` / `@declarative` decorate the very functions
    # we are asked to convert: strip them (the reference transformer drops
    # its own decorator the same way — dygraph_to_static/utils.py
    # remove_if_exist) rather than bailing. `@not_to_static` is an
    # explicit opt-out; anything else can't be re-applied faithfully to a
    # rebuilt copy, so leave the function unconverted with the warning.
    kept = []
    for dec in fdef.decorator_list:
        name = _decorator_tail(dec)
        if name in ("to_static", "declarative"):
            continue
        if name == "not_to_static":
            return fn
        kept.append(dec)
    if kept:
        warnings.warn(
            f"to_static: {fn.__qualname__} carries decorators; leaving it "
            "unconverted (tensor-dependent plain-Python control flow "
            "inside will fail under tracing)")
        return fn
    fdef.decorator_list = []

    tr = _ControlFlowTransformer()
    tr.visit(fdef)
    ast.fix_missing_locations(tree)
    if tr.skipped:
        warnings.warn(
            f"to_static: some control flow in {fn.__qualname__} uses "
            "return/yield inside if/loop bodies (break/continue alone "
            "are supported) and was left as plain Python (resolved at "
            "trace time; tensor-dependent predicates there will fail "
            "under tracing)")
    if not tr.changed:
        return fn                # nothing to do

    # closure variables become globals of the compiled copy
    namespace = dict(fn.__globals__)
    if fn.__closure__:
        namespace.update(zip(fn.__code__.co_freevars,
                             (c.cell_contents for c in fn.__closure__)))
    namespace["__jst_cond"] = _jst_cond
    namespace["__jst_while"] = _jst_while
    namespace["__jst_undef"] = _JST_UNDEF
    namespace["__jst_range"] = _jst_range
    namespace["__jst_rng_cond"] = _jst_rng_cond
    namespace["__jst_loop_ok"] = _jst_loop_ok
    namespace["__jst_not_any"] = _jst_not_any
    namespace["__jst_for_exit"] = _jst_for_exit
    if _CODE_LEVEL[0] > 0:
        print(f"[to_static] converted {fn.__qualname__}:")
        print(ast.unparse(tree))
    code = compile(tree, filename=f"<to_static {fn.__qualname__}>",
                   mode="exec")
    exec(code, namespace)
    converted = namespace[fdef.name]
    converted = functools.wraps(fn)(converted)
    converted.__jst_converted__ = True
    return converted


def maybe_convert(fn):
    """convert_function with idempotence (already-converted functions and
    bound methods pass through converted)."""
    if getattr(fn, "__jst_converted__", False):
        return fn
    if isinstance(fn, types.MethodType):
        conv = convert_function(fn.__func__)
        if conv is fn.__func__:
            return fn
        return types.MethodType(conv, fn.__self__)
    return convert_function(fn)


def convert_target(obj):
    """Apply the AST pass to a layer (rewriting its forward in place) or
    a plain function (returning the converted function) — the shared
    entry for StaticFunction and jit.save."""
    if hasattr(obj, "named_parameters"):
        conv = maybe_convert(obj.forward)
        if getattr(conv, "__jst_converted__", False) and not \
                getattr(obj.forward, "__jst_converted__", False):
            obj.forward = conv
        return obj
    return maybe_convert(obj)


_TRANSLATION_ENABLED = [True]
_VERBOSITY = [0]
_CODE_LEVEL = [0]


def enable_translation(flag):
    """ProgramTranslator.enable analog: globally toggles the AST pass."""
    _TRANSLATION_ENABLED[0] = bool(flag)


def translation_enabled():
    return _TRANSLATION_ENABLED[0]
