"""Statistics ops (reference: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import apply

__all__ = ["std", "var", "median", "nanmedian", "quantile", "nanquantile"]


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda a: jnp.var(a, axis=_axis(axis),
                                   ddof=1 if unbiased else 0,
                                   keepdims=keepdim), x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda a: jnp.std(a, axis=_axis(axis),
                                   ddof=1 if unbiased else 0,
                                   keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.median(a, axis=_axis(axis), keepdims=keepdim), x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nanmedian(a, axis=_axis(axis), keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply(lambda a: jnp.quantile(a, jnp.asarray(q), axis=_axis(axis),
                                        keepdims=keepdim, method=interpolation), x)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply(lambda a: jnp.nanquantile(a, jnp.asarray(q), axis=_axis(axis),
                                           keepdims=keepdim, method=interpolation), x)
