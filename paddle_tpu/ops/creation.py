"""Creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor, apply

__all__ = [
    "create_array", "array_length", "array_read", "array_write",
    "set_printoptions", "to_string",
    "zeros", "ones", "full", "zeros_like", "ones_like", "full_like",
    "arange", "linspace", "logspace", "eye", "empty", "empty_like", "tril",
    "triu", "diag", "diagflat", "meshgrid", "assign", "clone", "numel",
    "complex_", "as_tensor",
]


def _dt(dtype, default=None):
    d = dtype_mod.convert_dtype(dtype)
    if d is None:
        d = default if default is not None else dtype_mod.get_default_dtype()
    return d


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def zeros(shape, dtype=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None and isinstance(fill_value, (bool, int)):
        return Tensor(jnp.full(_shape(shape), fill_value))
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def zeros_like(x, dtype=None):
    return apply(lambda a: jnp.zeros_like(a, dtype=dtype_mod.convert_dtype(dtype)), x)


def ones_like(x, dtype=None):
    return apply(lambda a: jnp.ones_like(a, dtype=dtype_mod.convert_dtype(dtype)), x)


def full_like(x, fill_value, dtype=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return apply(lambda a: jnp.full_like(a, fill_value, dtype=dtype_mod.convert_dtype(dtype)), x)


def empty(shape, dtype=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = val(start), val(end), val(step)
    if end is None:
        start, end = 0, start
    d = dtype_mod.convert_dtype(dtype)
    if d is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            d = jnp.int64
        else:
            d = dtype_mod.get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.linspace(val(start), val(stop), int(val(num)),
                               dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None):
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None):
    return Tensor(jnp.eye(int(num_rows),
                          None if num_columns is None else int(num_columns),
                          dtype=_dt(dtype)))


def tril(x, diagonal=0):
    return apply(lambda a: jnp.tril(a, k=diagonal), x)


def triu(x, diagonal=0):
    return apply(lambda a: jnp.triu(a, k=diagonal), x)


def diag(x, offset=0, padding_value=0):
    def f(a):
        if a.ndim == 1 and padding_value != 0:
            d = jnp.diag(a, k=offset)
            mask = jnp.eye(d.shape[0], dtype=bool) if offset == 0 else \
                jnp.diag(jnp.ones_like(a, dtype=bool), k=offset)
            return jnp.where(mask, d, padding_value)
        return jnp.diag(a, k=offset)
    return apply(f, x)


def diagflat(x, offset=0):
    return apply(lambda a: jnp.diagflat(a, k=offset), x)


def meshgrid(*args):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return apply(lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")), *args)


def assign(x, output=None):
    src = x if isinstance(x, Tensor) else Tensor(np.asarray(x))
    out = apply(lambda a: a + 0 if jnp.issubdtype(a.dtype, jnp.inexact) else a,
                src, op_name="assign")
    if output is not None:
        output.set_value(out._data)
        return output
    return out


def clone(x):
    return x.clone()


def numel(x):
    return Tensor(jnp.asarray(x.size, jnp.int64))


def complex_(real, imag):
    return apply(lambda r, i: jax.lax.complex(r, i), real, imag)


def as_tensor(data, dtype=None, place=None):
    return Tensor(data, dtype=dtype, place=place)


import jax  # noqa: E402  (used by complex_)


# -- TensorArray surface (reference: tensor/array.py create_array/
# array_read/array_write/array_length over LoDTensorArray; static control
# flow stored arrays in Scope — here a plain Python list is the honest
# dygraph-parity container) ------------------------------------------------

def create_array(dtype="float32", initialized_list=None):
    arr = list(initialized_list) if initialized_list is not None else []
    for v in arr:
        if not isinstance(v, Tensor):
            raise TypeError("create_array initialized_list must hold "
                            f"Tensors, got {type(v)}")
    return arr


def array_length(array):
    return len(array)


def array_read(array, i):
    return array[int(i)]


def array_write(x, i, array=None):
    if array is None:
        array = []
    i = int(i)
    if i < len(array):
        array[i] = x
    elif i == len(array):
        array.append(x)
    else:
        raise IndexError(f"array_write index {i} beyond length "
                         f"{len(array)}")
    return array


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """numpy-backed print options (reference tensor/to_string.py)."""
    import numpy as np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def to_string(x):
    return repr(x)
