"""Pallas TPU kernels — the hot-op set the reference implements as
hand-written CUDA (operators/fused/ multihead_matmul, fused attention;
operators/optimizers/adam_op.cu; math/softmax.cu): here re-designed as
TPU Pallas kernels with jnp fallbacks off-TPU."""
from . import decode_attention  # noqa: F401
from . import flash_attention  # noqa: F401
