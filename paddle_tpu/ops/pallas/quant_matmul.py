"""Dequant-inside-matmul for int8 PTQ weights (quant/ptq.py layout).

A quantized decode weight is an int8 ``[in, out]`` tensor plus a
per-output-channel fp32 scale ``[out]`` (``w ~= q * scale``). Because
the scale is constant along the contraction axis it factors out of the
dot product::

    x @ (q * scale) == (x @ q) * scale

so dequantization costs one [*, out] multiply after the GEMV instead of
materializing an fp32 copy of the weight. Decode activations are skinny
(a handful of rows per step), so the Pallas kernel keeps the whole
operand set in VMEM as a single block — no tiling grid. The XLA
fallback is the same two-op composition; dispatch follows the existing
`PADDLE_TPU_DECODE_KERNEL=pallas|xla` knob.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import flags as _flags
from . import _common
from ._common import VMEM

try:
    from jax.experimental import pallas as pl
except Exception:  # pragma: no cover - pallas ships with jax
    pl = None

_ENV = "PADDLE_TPU_DECODE_KERNEL"


def int8_weight_matmul_reference(x, w_q, scale):
    """XLA fallback: ``(x @ q) * scale`` with an f32 accumulate."""
    acc = jax.lax.dot_general(
        x.astype(jnp.float32), w_q.astype(jnp.float32),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (acc * scale).astype(x.dtype)


def _mm_kernel(x_ref, w_ref, s_ref, o_ref):
    acc = jax.lax.dot(
        x_ref[...].astype(jnp.float32), w_ref[...].astype(jnp.float32),
        preferred_element_type=jnp.float32)
    o_ref[...] = (acc * s_ref[...]).astype(o_ref.dtype)


def _int8_weight_matmul_pallas(x, w_q, scale):
    lead = x.shape[:-1]
    K = x.shape[-1]
    N = w_q.shape[-1]
    x2 = x.reshape(-1, K)
    M = x2.shape[0]
    out = pl.pallas_call(
        _mm_kernel,
        in_specs=[
            pl.BlockSpec(memory_space=VMEM),
            pl.BlockSpec(memory_space=VMEM),
            pl.BlockSpec(memory_space=VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=VMEM),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        interpret=_common.interpret(),
    )(x2, w_q, scale.reshape(1, N))
    return out.reshape(*lead, N)


def int8_weight_matmul(x, w_q, scale, kernel=None):
    """Dispatch on `kernel` (or $PADDLE_TPU_DECODE_KERNEL, default xla)."""
    choice = (kernel or _flags.env_value(_ENV)).strip().lower()
    if choice == "pallas":
        return _int8_weight_matmul_pallas(x, w_q, scale)
    if choice in ("", "xla"):
        return int8_weight_matmul_reference(x, w_q, scale)
    raise ValueError(
        f"{_ENV}={choice!r}: expected 'pallas' or 'xla'")
