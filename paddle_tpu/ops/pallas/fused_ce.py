"""Fused linear + softmax-cross-entropy for TPU in Pallas.

The LM head is the single most bandwidth-hungry op in GPT training: logits
are [tokens, vocab] (824 MB bf16 for GPT-2's 8192x50304 step) and the naive
path materialises them in HBM several times (fwd matmul out, f32
log_softmax, dlogits). This kernel computes x @ W^T block-by-block in VMEM
with an online logsumexp, so full logits NEVER reach HBM; the backward
recomputes each logits block and feeds the MXU directly with
dlogits = (softmax - onehot) * g.

Replaces the reference's softmax_with_cross_entropy fused CUDA op
(/root/reference/paddle/fluid/operators/softmax_with_cross_entropy_op.cu)
and goes further by folding in the projection matmul (the reference has no
fused head; this is where TPU HBM bandwidth demands it).

Layouts: x [N, H], w [V, H] (row-major vocab), labels [N] int32.
Returns per-row loss [N] f32; callers apply mean/masking.
Vocab is padded internally to a multiple of the v-block; padded columns are
masked to -inf so they contribute nothing to lse or gradients.

Measured v5e crossover (N=8192, H=768, V=50304, bf16): fused 18.0 ms vs
XLA-materialised 13.2 ms fwd+bwd — the two recompute matmul passes cost more
than the saved HBM traffic at this geometry, so GPT-2-class models keep the
XLA path. The fused kernel wins when logits no longer fit cheap HBM streams
(long sequence chunks, >100k vocab, or memory-limited batch); exposed as
`nn.functional.linear_cross_entropy` with `fused=True|False|None(auto)`.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ._common import (pltpu, VMEM as _VMEM, on_tpu as _on_tpu,
                      mxu_dtype as _mxu_dtype, NEG_INF, LANE, I0 as _I0)


def _blocks(N, V, H=768, itemsize=2):
    """Tile sizes under the 16 MB VMEM budget. The bwd working set per
    grid step is ~(2*bn + 2*bv)*H*itemsize B of double-buffered x/w
    tiles + (bn+bv)*H*4 B f32 scratch/out + 2*bn*bv*4 B f32 logit
    tiles. The caps key on H*itemsize (bytes per row): bf16 rows at
    H <= 1024 fit the (512, 1024) tiles (~13 MB); H = 2048 bf16 — or
    H = 1024 f32 — hit 19-20 MB (both observed as compile-time VMEM
    stack OOMs), so each doubling of the row bytes halves the caps."""
    row_bytes = H * max(int(itemsize), 1)
    if row_bytes <= 2048:
        cap_n, cap_v = 512, 1024
    elif row_bytes <= 4096:
        cap_n, cap_v = 256, 512
    else:
        cap_n, cap_v = 128, 256
    bn = cap_n
    while bn > 128 and N % bn:
        bn //= 2
    return bn, cap_v


# ---------------------------------------------------------------------------
# forward kernel: grid (nN, nV); scratch carries (m, l, lab) over the v loop
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, lbl_ref, lse_ref, lab_ref, m_sc, l_sc, lab_sc,
                *, bn, bv, nv, V, mxu):
    vj = pl.program_id(1)

    @pl.when(vj == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc[:], NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc[:])
        lab_sc[:] = jnp.zeros_like(lab_sc[:])

    x = x_ref[...].astype(mxu)                       # [bn, H]
    w = w_ref[...].astype(mxu)                       # [bv, H]
    lg = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)  # [bn, bv]
    cols = vj * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    lg = jnp.where(cols < V, lg, NEG_INF)            # mask vocab padding

    lbl = lbl_ref[...]                               # [bn, 1] int32
    hit = cols == lbl
    lab_sc[:] = lab_sc[:] + jnp.sum(
        jnp.where(hit, lg, 0.0), axis=1, keepdims=True)

    m_prev = m_sc[:, :1]
    m_new = jnp.maximum(m_prev, lg.max(axis=1, keepdims=True))
    l_sc[:, :1] = l_sc[:, :1] * jnp.exp(m_prev - m_new) + jnp.sum(
        jnp.exp(lg - m_new), axis=1, keepdims=True)
    m_sc[:, :1] = m_new

    @pl.when(vj == nv - 1)
    def _finish():
        m = m_sc[:, :1]
        l = jnp.maximum(l_sc[:, :1], np.float32(1e-30))
        lse_ref[...] = jnp.broadcast_to(m + jnp.log(l), lse_ref.shape)
        lab_ref[...] = jnp.broadcast_to(lab_sc[:, :1], lab_ref.shape)


def _fwd_pallas(x, w, labels, V):
    N, H = x.shape
    Vp = w.shape[0]
    bn, bv = _blocks(N, Vp, H, x.dtype.itemsize)
    assert Vp % bv == 0, f"padded vocab {Vp} must divide v-block {bv}"
    nn, nv = N // bn, Vp // bv
    lbl2 = labels.astype(jnp.int32).reshape(N, 1)
    kern = functools.partial(_fwd_kernel, bn=bn, bv=bv, nv=nv, V=V,
                             mxu=_mxu_dtype())
    kwargs = {}
    if pltpu is not None and _on_tpu():
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))
    lse, lab = pl.pallas_call(
        kern,
        grid=(nn, nv),
        in_specs=[
            pl.BlockSpec((bn, H), lambda i, j: (i, _I0), memory_space=_VMEM),
            pl.BlockSpec((bv, H), lambda i, j: (j, _I0), memory_space=_VMEM),
            pl.BlockSpec((bn, 1), lambda i, j: (i, _I0), memory_space=_VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bn, LANE), lambda i, j: (i, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((bn, LANE), lambda i, j: (i, _I0),
                         memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, LANE), jnp.float32),
            jax.ShapeDtypeStruct((N, LANE), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, LANE), jnp.float32),
            pltpu.VMEM((bn, LANE), jnp.float32),
            pltpu.VMEM((bn, 1), jnp.float32),
        ] if pltpu is not None else [],
        interpret=not _on_tpu(),
        **kwargs,
    )(x, w, lbl2)
    return lse[:, 0], lab[:, 0]


# ---------------------------------------------------------------------------
# backward dx pass: grid (nN, nV), recompute logits block, dx scratch
# ---------------------------------------------------------------------------

def _bwd_dx_kernel(x_ref, w_ref, lbl_ref, lse_ref, g_ref, dx_ref, dx_sc,
                   *, bn, bv, nv, V, mxu):
    vj = pl.program_id(1)

    @pl.when(vj == 0)
    def _init():
        dx_sc[:] = jnp.zeros_like(dx_sc[:])

    x = x_ref[...].astype(mxu)
    w = w_ref[...].astype(mxu)
    lg = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    cols = vj * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    lg = jnp.where(cols < V, lg, NEG_INF)
    p = jnp.exp(lg - lse_ref[:, :1])
    onehot = (cols == lbl_ref[...]).astype(jnp.float32)
    dlg = ((p - onehot) * g_ref[:, :1]).astype(mxu)
    dx_sc[:] = dx_sc[:] + jax.lax.dot_general(
        dlg, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(vj == nv - 1)
    def _finish():
        dx_ref[...] = dx_sc[:].astype(dx_ref.dtype)


# ---------------------------------------------------------------------------
# backward dw pass: grid (nV, nN), recompute logits block, dw scratch
# ---------------------------------------------------------------------------

def _bwd_dw_kernel(x_ref, w_ref, lbl_ref, lse_ref, g_ref, dw_ref, dw_sc,
                   *, bn, bv, nn, V, mxu):
    vi = pl.program_id(0)
    nj = pl.program_id(1)

    @pl.when(nj == 0)
    def _init():
        dw_sc[:] = jnp.zeros_like(dw_sc[:])

    x = x_ref[...].astype(mxu)                       # [bn, H]
    w = w_ref[...].astype(mxu)                       # [bv, H]
    lg = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)  # [bn, bv]
    cols = vi * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    lg = jnp.where(cols < V, lg, NEG_INF)
    p = jnp.exp(lg - lse_ref[:, :1])
    onehot = (cols == lbl_ref[...]).astype(jnp.float32)
    dlg = ((p - onehot) * g_ref[:, :1]).astype(mxu)  # [bn, bv]
    dw_sc[:] = dw_sc[:] + jax.lax.dot_general(
        dlg, x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [bv, H]

    @pl.when(nj == nn - 1)
    def _finish():
        dw_ref[...] = dw_sc[:].astype(dw_ref.dtype)


def _bwd_pallas(x, w, labels, lse, g, V):
    N, H = x.shape
    Vp = w.shape[0]
    bn, bv = _blocks(N, Vp, H, x.dtype.itemsize)
    assert Vp % bv == 0, f"padded vocab {Vp} must divide v-block {bv}"
    nn, nv = N // bn, Vp // bv
    lbl2 = labels.astype(jnp.int32).reshape(N, 1)
    lse2 = jnp.broadcast_to(lse[:, None], (N, LANE))
    g2 = jnp.broadcast_to(g.astype(jnp.float32)[:, None], (N, LANE))
    mxu = _mxu_dtype()
    kwargs = {}
    if pltpu is not None and _on_tpu():
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))

    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, bn=bn, bv=bv, nv=nv, V=V, mxu=mxu),
        grid=(nn, nv),
        in_specs=[
            pl.BlockSpec((bn, H), lambda i, j: (i, _I0), memory_space=_VMEM),
            pl.BlockSpec((bv, H), lambda i, j: (j, _I0), memory_space=_VMEM),
            pl.BlockSpec((bn, 1), lambda i, j: (i, _I0), memory_space=_VMEM),
            pl.BlockSpec((bn, LANE), lambda i, j: (i, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((bn, LANE), lambda i, j: (i, _I0),
                         memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((bn, H), lambda i, j: (i, _I0),
                               memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((N, H), x.dtype),
        scratch_shapes=[pltpu.VMEM((bn, H), jnp.float32)]
        if pltpu is not None else [],
        interpret=not _on_tpu(),
        **kwargs,
    )(x, w, lbl2, lse2, g2)

    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, bn=bn, bv=bv, nn=nn, V=V, mxu=mxu),
        grid=(nv, nn),
        in_specs=[
            pl.BlockSpec((bn, H), lambda i, j: (j, _I0), memory_space=_VMEM),
            pl.BlockSpec((bv, H), lambda i, j: (i, _I0), memory_space=_VMEM),
            pl.BlockSpec((bn, 1), lambda i, j: (j, _I0), memory_space=_VMEM),
            pl.BlockSpec((bn, LANE), lambda i, j: (j, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((bn, LANE), lambda i, j: (j, _I0),
                         memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((bv, H), lambda i, j: (i, _I0),
                               memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((Vp, H), w.dtype),
        scratch_shapes=[pltpu.VMEM((bv, H), jnp.float32)]
        if pltpu is not None else [],
        interpret=not _on_tpu(),
        **kwargs,
    )(x, w, lbl2, lse2, g2)
    return dx, dw


# ---------------------------------------------------------------------------
# XLA fallback (CPU tests / any-shape): chunked custom path, same residuals
# ---------------------------------------------------------------------------

def _xla_fwd(x, w, labels, V):
    lg = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if w.shape[0] != V:
        cols = jnp.arange(w.shape[0])
        lg = jnp.where(cols[None, :] < V, lg, NEG_INF)
    m = lg.max(axis=1)
    l = jnp.sum(jnp.exp(lg - m[:, None]), axis=1)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    lab = jnp.take_along_axis(lg, labels.astype(jnp.int32)[:, None],
                              axis=1)[:, 0]
    return lse, lab


def _xla_bwd(x, w, labels, lse, g, V):
    lg = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    Vp = w.shape[0]
    cols = jax.lax.broadcasted_iota(jnp.int32, lg.shape, 1)
    if Vp != V:
        lg = jnp.where(cols < V, lg, NEG_INF)
    p = jnp.exp(lg - lse[:, None])
    onehot = (cols == labels.astype(jnp.int32)[:, None]).astype(jnp.float32)
    dlg = ((p - onehot) * g.astype(jnp.float32)[:, None]).astype(x.dtype)
    dx = (dlg @ w).astype(x.dtype)
    dw = jax.lax.dot_general(dlg, x, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32).astype(
                                 w.dtype)
    return dx, dw


# ---------------------------------------------------------------------------
# public entry: per-row CE loss with custom VJP, vocab padded to block size
# ---------------------------------------------------------------------------

def _pad_vocab(w, bv=1024):
    V = w.shape[0]
    Vp = ((V + bv - 1) // bv) * bv
    if Vp != V:
        w = jnp.pad(w, ((0, Vp - V), (0, 0)))
    return w


def _pallas_ok(N, H):
    return _on_tpu() and N % 128 == 0 and H % 128 == 0


@jax.custom_vjp
def _lce_pallas(x, w, labels):
    loss, _ = _lce_pallas_fwd(x, w, labels)
    return loss


def _lce_pallas_fwd(x, w, labels):
    V = w.shape[0]
    wp = _pad_vocab(w, bv=_blocks(x.shape[0], V, x.shape[1],
                                  x.dtype.itemsize)[1])
    lse, lab = _fwd_pallas(x, wp, labels, V)
    return lse - lab, (x, w, labels, lse)


def _lce_pallas_bwd(res, g):
    x, w, labels, lse = res
    V = w.shape[0]
    wp = _pad_vocab(w, bv=_blocks(x.shape[0], V, x.shape[1],
                                  x.dtype.itemsize)[1])
    dx, dwp = _bwd_pallas(x, wp, labels, lse, g, V)
    return dx, dwp[:V], None


_lce_pallas.defvjp(_lce_pallas_fwd, _lce_pallas_bwd)


@jax.custom_vjp
def _lce_xla(x, w, labels):
    loss, _ = _lce_xla_fwd(x, w, labels)
    return loss


def _lce_xla_fwd(x, w, labels):
    V = w.shape[0]
    lse, lab = _xla_fwd(x, w, labels, V)
    return lse - lab, (x, w, labels, lse)


def _lce_xla_bwd(res, g):
    x, w, labels, lse = res
    dx, dw = _xla_bwd(x, w, labels, lse, g, w.shape[0])
    return dx, dw, None


_lce_xla.defvjp(_lce_xla_fwd, _lce_xla_bwd)


def linear_cross_entropy(x, w, labels, fused=None):
    """loss[i] = -log softmax(x[i] @ w.T)[labels[i]]; x [N,H], w [V,H].

    fused=None picks the Pallas kernel on TPU when the logits matrix is
    large enough that avoiding its HBM materialisation beats the recompute
    matmuls (measured crossover ~V=64k at H<=1024 on v5e); True forces the
    kernel (shapes permitting), False forces the XLA path.
    """
    N, H = x.shape
    V = w.shape[0]
    if fused is None:
        fused = _pallas_ok(N, H) and V >= 65536
    elif fused:
        fused = _pallas_ok(N, H)
    return _lce_pallas(x, w, labels) if fused else _lce_xla(x, w, labels)
