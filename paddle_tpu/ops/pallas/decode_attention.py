"""Single-token (q_len == 1) decode attention for the KV-cache path.

During autoregressive decode every step attends one fresh query row per
sequence against that sequence's cached K/V — a GEMV per head, not the
GEMM the flash kernel is tiled for. This module provides:

  * `decode_attention_reference` — the jnp/XLA composition (masked
    softmax over the cache capacity). Always available, used by the
    correctness gate and as the default serving path.
  * `_decode_attention_pallas` — a Pallas kernel, one grid cell per
    (batch, head) pair: the query row and its cache panel live in VMEM,
    the score GEMV, masked softmax and output GEMV never round-trip
    through HBM between ops. Runs in interpret mode off-TPU so the CPU
    test suite exercises the same kernel body.
  * `decode_attention` — the dispatch point, selected by
    `PADDLE_TPU_DECODE_KERNEL=pallas|xla` (default `xla`; the Pallas
    path is opt-in until it has TPU soak time).

The paged trio (`paged_decode_attention[_reference]` and its Pallas
kernel) attends the same math over a PAGED cache: a shared page pool
plus per-sequence int32 block tables (inference/decode.py's paged
engine). The Pallas variant walks the block table via scalar-prefetch
index maps — one grid cell per (batch, head, page), online softmax in
scratch — so only mapped pages are ever streamed into VMEM; the XLA
fallback gathers pages with `jnp.take`.

Shapes (cap = KV-cache capacity rung, see inference/decode.py):

    q        [B, H, D]        fresh query row per sequence
    k, v     [B, cap, H, D]   cache panels (rows >= length are garbage)
    lengths  [B] int32        valid prefix per sequence (masks the rest)
    out      [B, H, D]
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ...core import flags as _flags
from . import _common
from ._common import NEG_INF, VMEM, I0 as _I0, pltpu

try:
    from jax.experimental import pallas as pl
except Exception:  # pragma: no cover - pallas ships with jax
    pl = None

_ENV = "PADDLE_TPU_DECODE_KERNEL"


def decode_attention_reference(q, k, v, lengths):
    """jnp reference: masked softmax(q.k/sqrt(D)).v over cache rows."""
    B, cap, H, D = k.shape
    scale = 1.0 / math.sqrt(D)
    s = jnp.einsum("bhd,bkhd->bhk", q, k) * scale
    s = s.astype(jnp.float32)
    live = jnp.arange(cap, dtype=jnp.int32)[None, None, :] \
        < lengths.astype(jnp.int32)[:, None, None]
    s = jnp.where(live, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    o = jnp.einsum("bhk,bkhd->bhd", p, v)
    return o.astype(q.dtype)


def _kernel(q_ref, k_ref, v_ref, m_ref, o_ref, *, scale):
    q = q_ref[0]                                   # [1, D]
    kp = k_ref[0]                                  # [cap, D]
    vp = v_ref[0]
    s = jax.lax.dot_general(
        q, kp, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale       # [1, cap]
    s = s + m_ref[0]                               # additive 0 / -inf mask
    p = jax.nn.softmax(s, axis=-1)
    o = jax.lax.dot(p.astype(vp.dtype), vp,
                    preferred_element_type=jnp.float32)   # [1, D]
    o_ref[0] = o.astype(o_ref.dtype)


def _decode_attention_pallas(q, k, v, lengths):
    B, cap, H, D = k.shape
    BH = B * H
    scale = 1.0 / math.sqrt(D)
    q3 = q.reshape(BH, 1, D)
    k3 = jnp.transpose(k, (0, 2, 1, 3)).reshape(BH, cap, D)
    v3 = jnp.transpose(v, (0, 2, 1, 3)).reshape(BH, cap, D)
    # additive mask rides VMEM instead of per-cell SMEM scalars: one
    # [1, cap] row per grid cell, 0 on live rows, -inf on dead ones
    live = jnp.arange(cap, dtype=jnp.int32)[None, :] \
        < lengths.astype(jnp.int32)[:, None]                  # [B, cap]
    mask = jnp.where(live, 0.0, NEG_INF).astype(jnp.float32)
    mask3 = jnp.repeat(mask[:, None, :], H, axis=0).reshape(BH, 1, cap)

    kw = {}
    if pltpu is not None and not _common.interpret():
        kw["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",))
    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale),
        grid=(BH,),
        in_specs=[
            pl.BlockSpec((1, 1, D), lambda i: (i, _I0, _I0),
                         memory_space=VMEM),
            pl.BlockSpec((1, cap, D), lambda i: (i, _I0, _I0),
                         memory_space=VMEM),
            pl.BlockSpec((1, cap, D), lambda i: (i, _I0, _I0),
                         memory_space=VMEM),
            pl.BlockSpec((1, 1, cap), lambda i: (i, _I0, _I0),
                         memory_space=VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, D), lambda i: (i, _I0, _I0),
                               memory_space=VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, 1, D), q.dtype),
        interpret=_common.interpret(),
        **kw,
    )(q3, k3, v3, mask3)
    return out.reshape(B, H, D)


def decode_attention(q, k, v, lengths, kernel=None):
    """Dispatch on `kernel` (or $PADDLE_TPU_DECODE_KERNEL, default xla)."""
    choice = (kernel or _flags.env_value(_ENV)).strip().lower()
    if choice == "pallas":
        return _decode_attention_pallas(q, k, v, lengths)
    if choice in ("", "xla"):
        return decode_attention_reference(q, k, v, lengths)
    raise ValueError(
        f"{_ENV}={choice!r}: expected 'pallas' or 'xla'")


# ---------------------------------------------------------------------------
# Paged variant: the cache is a shared page pool + per-sequence block table
# ---------------------------------------------------------------------------
#
#     q        [B, H, D]          fresh query row per sequence
#     k_pool   [P, pt, H, D]      one layer's page pool (pt = page tokens)
#     v_pool   [P, pt, H, D]
#     tables   [B, W] int32       block table: tables[b, w] = page holding
#                                 rows [w*pt, (w+1)*pt) of sequence b;
#                                 unused entries point at the null page
#     lengths  [B] int32          valid prefix per sequence
#     out      [B, H, D]

def paged_decode_attention_reference(q, k_pool, v_pool, tables, lengths):
    """XLA fallback: gather the table's pages (`jnp.take`), flatten to a
    contiguous [B, W*pt, H, D] view, reuse the masked-softmax math."""
    B, W = tables.shape
    P, pt, H, D = k_pool.shape
    k = jnp.take(k_pool, tables, axis=0).reshape(B, W * pt, H, D)
    v = jnp.take(v_pool, tables, axis=0).reshape(B, W * pt, H, D)
    return decode_attention_reference(q, k, v, lengths)


def _paged_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  m_s, l_s, acc_s, *, scale, pt):
    """One grid cell per (batch, head, page-slot): walk the block table
    along the last grid dim with online (flash-style) softmax carried in
    SMEM/VMEM scratch, so only the pages a sequence actually maps stream
    through VMEM — no gather materialization."""
    b = pl.program_id(0)
    w = pl.program_id(2)

    @pl.when(w == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    qv = q_ref[0, 0]                         # [1, D]
    kp = k_ref[0, :, 0, :]                   # [pt, D] one page, one head
    vp = v_ref[0, :, 0, :]
    s = jax.lax.dot_general(
        qv, kp, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale        # [1, pt]
    rows = w * pt + jax.lax.broadcasted_iota(jnp.int32, (1, pt), 1)
    s = jnp.where(rows < len_ref[b], s, NEG_INF)
    m_prev = m_s[0, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                                 # [1, pt]
    m_s[0, 0] = m_new
    l_s[0, 0] = l_s[0, 0] * corr + jnp.sum(p)
    acc_s[...] = acc_s[...] * corr + jax.lax.dot(
        p.astype(vp.dtype), vp, preferred_element_type=jnp.float32)

    @pl.when(w == pl.num_programs(2) - 1)
    def _emit():
        o_ref[0, 0] = (acc_s[...] / l_s[0, 0]).astype(o_ref.dtype)


def _paged_decode_attention_pallas(q, k_pool, v_pool, tables, lengths):
    B, H, D = q.shape
    P, pt, _, _ = k_pool.shape
    W = tables.shape[1]
    scale = 1.0 / math.sqrt(D)
    # scalar-prefetch carries (tables, lengths): their VALUES drive the
    # K/V index_map, so each grid cell DMAs exactly the page the block
    # table names — the table walk happens in the pipeline, not the body
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, W),
        in_specs=[
            pl.BlockSpec((1, 1, 1, D),
                         lambda b, h, w, tbl, ln: (b, h, 0, 0)),
            pl.BlockSpec((1, pt, 1, D),
                         lambda b, h, w, tbl, ln: (tbl[b, w], 0, h, 0)),
            pl.BlockSpec((1, pt, 1, D),
                         lambda b, h, w, tbl, ln: (tbl[b, w], 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, 1, D),
                               lambda b, h, w, tbl, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.SMEM((1, 1), jnp.float32),     # running max
            pltpu.SMEM((1, 1), jnp.float32),     # running denominator
            pltpu.VMEM((1, D), jnp.float32),     # output accumulator
        ],
    )
    kw = {}
    if not _common.interpret():
        kw["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"))
    out = pl.pallas_call(
        functools.partial(_paged_kernel, scale=scale, pt=pt),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, 1, D), q.dtype),
        interpret=_common.interpret(),
        **kw,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32),
      q.reshape(B, H, 1, D), k_pool, v_pool)
    return out.reshape(B, H, D)


def paged_decode_attention(q, k_pool, v_pool, tables, lengths, kernel=None):
    """Dispatch on `kernel` (or $PADDLE_TPU_DECODE_KERNEL, default xla)."""
    choice = (kernel or _flags.env_value(_ENV)).strip().lower()
    if choice == "pallas":
        return _paged_decode_attention_pallas(q, k_pool, v_pool,
                                              tables, lengths)
    if choice in ("", "xla"):
        return paged_decode_attention_reference(q, k_pool, v_pool,
                                                tables, lengths)
    raise ValueError(
        f"{_ENV}={choice!r}: expected 'pallas' or 'xla'")


# ---------------------------------------------------------------------------
# Int8 paged variant: fused dequant-inside-GEMV over quantized page pools
# ---------------------------------------------------------------------------
#
# The int8 pool (quant/kv.py) splits each fp32 K/V pool into an int8
# payload plus a per-(token row, head) fp32 scale:
#
#     k_pool, v_pool    [P, pt, H, D] int8
#     k_scale, v_scale  [P, pt, H]    f32   (row = q * scale)
#
# The Pallas kernel prefetches the scale page alongside its int8 page
# and dequantizes in-register right before the online-softmax
# accumulate — the fp32 panel never exists in HBM.

def paged_decode_attention_quant_reference(q, k_pool, k_scale,
                                           v_pool, v_scale,
                                           tables, lengths):
    """XLA fallback: gather int8 pages + scales, dequantize the gathered
    panel, reuse the fp32 masked-softmax math."""
    B, W = tables.shape
    P, pt, H, D = k_pool.shape
    k = (jnp.take(k_pool, tables, axis=0).astype(jnp.float32)
         * jnp.take(k_scale, tables, axis=0)[..., None])
    v = (jnp.take(v_pool, tables, axis=0).astype(jnp.float32)
         * jnp.take(v_scale, tables, axis=0)[..., None])
    k = k.reshape(B, W * pt, H, D)
    v = v.reshape(B, W * pt, H, D)
    return decode_attention_reference(q, k, v, lengths)


def _paged_quant_kernel(tbl_ref, len_ref, q_ref, k_ref, ks_ref,
                        v_ref, vs_ref, o_ref, m_s, l_s, acc_s,
                        *, scale, pt):
    """`_paged_kernel` with int8 pages: the scale row rides its own
    prefetched block and the page dequantizes in-register before the
    score GEMV / accumulate."""
    b = pl.program_id(0)
    w = pl.program_id(2)

    @pl.when(w == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    qv = q_ref[0, 0]                                       # [1, D]
    ks = ks_ref[0, 0]                                      # [pt]
    vs = vs_ref[0, 0]
    kp = k_ref[0, :, 0, :].astype(jnp.float32) * ks[:, None]   # [pt, D]
    vp = v_ref[0, :, 0, :].astype(jnp.float32) * vs[:, None]
    s = jax.lax.dot_general(
        qv, kp, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale        # [1, pt]
    rows = w * pt + jax.lax.broadcasted_iota(jnp.int32, (1, pt), 1)
    s = jnp.where(rows < len_ref[b], s, NEG_INF)
    m_prev = m_s[0, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                                 # [1, pt]
    m_s[0, 0] = m_new
    l_s[0, 0] = l_s[0, 0] * corr + jnp.sum(p)
    acc_s[...] = acc_s[...] * corr + jax.lax.dot(
        p, vp, preferred_element_type=jnp.float32)

    @pl.when(w == pl.num_programs(2) - 1)
    def _emit():
        o_ref[0, 0] = (acc_s[...] / l_s[0, 0]).astype(o_ref.dtype)


def _paged_decode_attention_quant_pallas(q, k_pool, k_scale,
                                         v_pool, v_scale,
                                         tables, lengths):
    B, H, D = q.shape
    P, pt, _, _ = k_pool.shape
    W = tables.shape[1]
    scale = 1.0 / math.sqrt(D)
    # scales land lane-major ([P, H, pt]) so each grid cell's scale row
    # is one contiguous [1, 1, pt] block next to its int8 page
    ks = jnp.transpose(k_scale, (0, 2, 1))
    vs = jnp.transpose(v_scale, (0, 2, 1))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, W),
        in_specs=[
            pl.BlockSpec((1, 1, 1, D),
                         lambda b, h, w, tbl, ln: (b, h, 0, 0)),
            pl.BlockSpec((1, pt, 1, D),
                         lambda b, h, w, tbl, ln: (tbl[b, w], 0, h, 0)),
            pl.BlockSpec((1, 1, pt),
                         lambda b, h, w, tbl, ln: (tbl[b, w], h, 0)),
            pl.BlockSpec((1, pt, 1, D),
                         lambda b, h, w, tbl, ln: (tbl[b, w], 0, h, 0)),
            pl.BlockSpec((1, 1, pt),
                         lambda b, h, w, tbl, ln: (tbl[b, w], h, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, 1, D),
                               lambda b, h, w, tbl, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.SMEM((1, 1), jnp.float32),     # running max
            pltpu.SMEM((1, 1), jnp.float32),     # running denominator
            pltpu.VMEM((1, D), jnp.float32),     # output accumulator
        ],
    )
    kw = {}
    if not _common.interpret():
        kw["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"))
    out = pl.pallas_call(
        functools.partial(_paged_quant_kernel, scale=scale, pt=pt),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, 1, D), q.dtype),
        interpret=_common.interpret(),
        **kw,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32),
      q.reshape(B, H, 1, D), k_pool, ks, v_pool, vs)
    return out.reshape(B, H, D)


def paged_decode_attention_quant(q, k_pool, k_scale, v_pool, v_scale,
                                 tables, lengths, kernel=None):
    """Dispatch on `kernel` (or $PADDLE_TPU_DECODE_KERNEL, default xla)."""
    choice = (kernel or _flags.env_value(_ENV)).strip().lower()
    if choice == "pallas":
        return _paged_decode_attention_quant_pallas(
            q, k_pool, k_scale, v_pool, v_scale, tables, lengths)
    if choice in ("", "xla"):
        return paged_decode_attention_quant_reference(
            q, k_pool, k_scale, v_pool, v_scale, tables, lengths)
    raise ValueError(
        f"{_ENV}={choice!r}: expected 'pallas' or 'xla'")
