"""Single-token (q_len == 1) decode attention for the KV-cache path.

During autoregressive decode every step attends one fresh query row per
sequence against that sequence's cached K/V — a GEMV per head, not the
GEMM the flash kernel is tiled for. This module provides:

  * `decode_attention_reference` — the jnp/XLA composition (masked
    softmax over the cache capacity). Always available, used by the
    correctness gate and as the default serving path.
  * `_decode_attention_pallas` — a Pallas kernel, one grid cell per
    (batch, head) pair: the query row and its cache panel live in VMEM,
    the score GEMV, masked softmax and output GEMV never round-trip
    through HBM between ops. Runs in interpret mode off-TPU so the CPU
    test suite exercises the same kernel body.
  * `decode_attention` — the dispatch point, selected by
    `PADDLE_TPU_DECODE_KERNEL=pallas|xla` (default `xla`; the Pallas
    path is opt-in until it has TPU soak time).

Shapes (cap = KV-cache capacity rung, see inference/decode.py):

    q        [B, H, D]        fresh query row per sequence
    k, v     [B, cap, H, D]   cache panels (rows >= length are garbage)
    lengths  [B] int32        valid prefix per sequence (masks the rest)
    out      [B, H, D]
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ...core import flags as _flags
from . import _common
from ._common import NEG_INF, VMEM, I0 as _I0, pltpu

try:
    from jax.experimental import pallas as pl
except Exception:  # pragma: no cover - pallas ships with jax
    pl = None

_ENV = "PADDLE_TPU_DECODE_KERNEL"


def decode_attention_reference(q, k, v, lengths):
    """jnp reference: masked softmax(q.k/sqrt(D)).v over cache rows."""
    B, cap, H, D = k.shape
    scale = 1.0 / math.sqrt(D)
    s = jnp.einsum("bhd,bkhd->bhk", q, k) * scale
    s = s.astype(jnp.float32)
    live = jnp.arange(cap, dtype=jnp.int32)[None, None, :] \
        < lengths.astype(jnp.int32)[:, None, None]
    s = jnp.where(live, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    o = jnp.einsum("bhk,bkhd->bhd", p, v)
    return o.astype(q.dtype)


def _kernel(q_ref, k_ref, v_ref, m_ref, o_ref, *, scale):
    q = q_ref[0]                                   # [1, D]
    kp = k_ref[0]                                  # [cap, D]
    vp = v_ref[0]
    s = jax.lax.dot_general(
        q, kp, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale       # [1, cap]
    s = s + m_ref[0]                               # additive 0 / -inf mask
    p = jax.nn.softmax(s, axis=-1)
    o = jax.lax.dot(p.astype(vp.dtype), vp,
                    preferred_element_type=jnp.float32)   # [1, D]
    o_ref[0] = o.astype(o_ref.dtype)


def _decode_attention_pallas(q, k, v, lengths):
    B, cap, H, D = k.shape
    BH = B * H
    scale = 1.0 / math.sqrt(D)
    q3 = q.reshape(BH, 1, D)
    k3 = jnp.transpose(k, (0, 2, 1, 3)).reshape(BH, cap, D)
    v3 = jnp.transpose(v, (0, 2, 1, 3)).reshape(BH, cap, D)
    # additive mask rides VMEM instead of per-cell SMEM scalars: one
    # [1, cap] row per grid cell, 0 on live rows, -inf on dead ones
    live = jnp.arange(cap, dtype=jnp.int32)[None, :] \
        < lengths.astype(jnp.int32)[:, None]                  # [B, cap]
    mask = jnp.where(live, 0.0, NEG_INF).astype(jnp.float32)
    mask3 = jnp.repeat(mask[:, None, :], H, axis=0).reshape(BH, 1, cap)

    kw = {}
    if pltpu is not None and not _common.interpret():
        kw["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",))
    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale),
        grid=(BH,),
        in_specs=[
            pl.BlockSpec((1, 1, D), lambda i: (i, _I0, _I0),
                         memory_space=VMEM),
            pl.BlockSpec((1, cap, D), lambda i: (i, _I0, _I0),
                         memory_space=VMEM),
            pl.BlockSpec((1, cap, D), lambda i: (i, _I0, _I0),
                         memory_space=VMEM),
            pl.BlockSpec((1, 1, cap), lambda i: (i, _I0, _I0),
                         memory_space=VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, D), lambda i: (i, _I0, _I0),
                               memory_space=VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, 1, D), q.dtype),
        interpret=_common.interpret(),
        **kw,
    )(q3, k3, v3, mask3)
    return out.reshape(B, H, D)


def decode_attention(q, k, v, lengths, kernel=None):
    """Dispatch on `kernel` (or $PADDLE_TPU_DECODE_KERNEL, default xla)."""
    choice = (kernel or _flags.env_value(_ENV)).strip().lower()
    if choice == "pallas":
        return _decode_attention_pallas(q, k, v, lengths)
    if choice in ("", "xla"):
        return decode_attention_reference(q, k, v, lengths)
    raise ValueError(
        f"{_ENV}={choice!r}: expected 'pallas' or 'xla'")
