"""Flash attention for TPU in Pallas — forward + flash backward custom VJP.

Replaces the reference's fused CUDA attention kernels
(/root/reference/paddle/fluid/operators/fused/multihead_matmul_op.cu,
operators/fused/fused_embedding_eltwise_layernorm) with the memory-optimal
online-softmax algorithm: O(T) memory instead of materialising the [T, T]
score matrix, K/V streamed block-by-block through VMEM into the MXU.

Layout: [B, T, H, D] (paddle sdpa convention) reshaped to [B*H, T, D].
Kernel structure is the TPU-canonical *grid-loop* form: the k-block loop is
the innermost ("arbitrary") grid dimension and the online-softmax state
(m, l, acc) lives in VMEM scratch that persists across those grid steps —
Mosaic pipelines the K/V block DMAs against MXU work. Causal pruning skips
above-diagonal blocks with pl.when. f32 accumulation via
preferred_element_type; bf16-friendly inputs.

Backward: a fused single-pass kernel (one score recompute emits dq, dk
and dv together) when the k sweep is single-block (T <= the k-block cap);
the standard two-pass scheme (dq pass over k blocks, dkv pass over q
blocks) above that. delta = rowsum(dO * O) is computed in-kernel in the
dkv/fused bodies. Saved residuals: q, k, v, o, logsumexp.

logsumexp is stored lane-replicated as [BH, T, 128] f32 — nominally 128x
the bytes of the per-row scalar, but keeping the lane dim lets every
kernel read/write it as a native (sublane, lane) tile with zero
relayouts; the extra HBM traffic is ~bq*128*4 per grid step (<0.5% of
the qkv streams; measured in the noise on the flagship bench), while a
[BH, T] layout would force a lane->sublane transpose inside each of the
three consumers.
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ._common import (pltpu, VMEM as _VMEM, interpret as _interpret,
                      mxu_dtype as _mxu_dtype, NEG_INF, LANE, I0 as _I0)


def _pick_block(T, cap):
    """Largest block <= cap that divides T, stepping down by powers of two
    from cap to 128; tiny sequences (T < 128) use one block."""
    if T <= 128:
        return T
    b = cap
    while b > 128 and T % b:
        b //= 2
    return b


def _env_blocks(key, T):
    bq, bk = (min(int(v), T) for v in os.environ[key].split(","))
    if bq <= 0 or bk <= 0 or T % bq or T % bk:
        raise ValueError(f"{key}={os.environ[key]}: blocks must be positive "
                         f"and divide seq len {T}")
    return bq, bk


def _block_sizes(T, D, env_key="PT_FLASH_FWD_BLOCKS"):
    """Large blocks amortise per-grid-step overhead: at (128,128) a T=1024
    head is 6k grid steps of ~4 MFLOP each and the kernel is dispatch-bound
    (measured 8.5 ms/layer fwd+bwd vs 3.9 ms at (512,1024) on v5e). The env
    keys PT_FLASH_{FWD,BWD}_BLOCKS are perf-tuning escape hatches.

    (1024, 1024) caps are the long-context sweep's optimum on v5e:
    every T in {1024..16384} lands >= 46% MFU vs the 42.5-44.5% tail the
    old (512, 1024) caps left at T >= 4096 (numbers + methodology:
    benchmarks/RESULTS.md long-context table; reproduce with
    benchmarks/longctx.py). 2048-wide blocks exceed VMEM at D=64 (the
    f32 score tile alone is 16 MB)."""
    if env_key in os.environ:
        return _env_blocks(env_key, T)
    return _pick_block(T, 1024), _pick_block(T, 1024)


def _bwd_block_sizes(T, D):
    """Backward caps get their own VMEM budget — the bwd working set is
    larger than the forward's. Per (bq, bk) grid step of the dkv kernel
    the f32 score-sized intermediates are s/p (reusable), dp and ds at
    bq*bk*4 B each (~3 live tiles), plus double-buffered I/O tiles
    (q/k/v/do/o bf16 + lse f32: ~(4*max(bq,bk)*D*2 + bq*128*4)*2 B) and
    the dk/dv f32 scratch (2*bk*D*4 B). At (1024, 1024):
      D=64 : 12 MB + 1.9 MB + 0.5 MB ~= 14.4 MB -> fits 16 MB VMEM
             (exercised fwd+bwd by the benchmarks/longctx.py training
             sweep at T=1k..16k, D=64 — the RESULTS.md numbers)
      D=128: 12 MB + 3.5 MB + 1.0 MB ~= 16.5 MB -> over budget, so wide
             heads cap bq at 512, halving the score tiles to 2 MB each
             (~9.75 MB total) with the same nk==1 fused-path eligibility
             (bk stays 1024). Measured cost of the halved caps: none —
             fwd+bwd at T=4096 on v5e runs 73.6 TF/s at D=128/(512,1024)
             vs 50.5 TF/s at D=64/(1024,1024) (the wider contraction
             feeds the MXU better)."""
    if "PT_FLASH_BWD_BLOCKS" in os.environ:
        return _env_blocks("PT_FLASH_BWD_BLOCKS", T)
    cap_q = 1024 if D <= 64 else 512
    return _pick_block(T, cap_q), _pick_block(T, 1024)


# ---------------------------------------------------------------------------
# forward kernel: grid (BH, nq, nk), scratch carries (m, l, acc) over nk
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc, *,
                scale, causal, block_q, block_k, nk, mxu):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc[:], NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc[:])
        acc_sc[:] = jnp.zeros_like(acc_sc[:])

    # causal: process only blocks intersecting the lower triangle
    should = (kj * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(should)
    def _step():
        # bf16 operands feed the MXU at full rate; accumulation stays f32
        q = (q_ref[0].astype(jnp.float32) * np.float32(scale)).astype(mxu)                                 # [bq, D]
        k = k_ref[0].astype(mxu)                 # [bk, D]
        v = v_ref[0].astype(mxu)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_prev = m_sc[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_sc[:, :1] + p.sum(axis=1, keepdims=True)
        acc_sc[:] = alpha * acc_sc[:] + jax.lax.dot_general(
            p.astype(mxu), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_sc[:] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new, l_sc.shape)

    @pl.when(kj == nk - 1)
    def _finish():
        l = jnp.maximum(l_sc[:, :1], np.float32(1e-30))
        o_ref[0] = (acc_sc[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_sc[:] + jnp.log(jnp.maximum(l_sc[:], np.float32(1e-30)))


def _fwd(q3, k3, v3, scale, causal):
    BH, T, D = q3.shape
    bq, bk = _block_sizes(T, D)
    nq, nk = T // bq, T // bk
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             block_q=bq, block_k=bk, nk=nk, mxu=_mxu_dtype())
    kwargs = {}
    if pltpu is not None and not _interpret():
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    o, lse = pl.pallas_call(
        kern,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, _I0),
                         memory_space=_VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bq, LANE), lambda b, i, j: (b, i, _I0),
                         memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, T, LANE), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, LANE), jnp.float32),
            pltpu.VMEM((bq, LANE), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ] if pltpu is not None else [],
        interpret=_interpret(),
        **kwargs,
    )(q3, k3, v3)
    return o, lse


# ---------------------------------------------------------------------------
# backward: dq pass (grid over q blocks x k blocks, dq scratch)
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_sc, *, scale, causal, block_q, block_k, nk, mxu):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc[:])

    should = (kj * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(should)
    def _step():
        q = (q_ref[0].astype(jnp.float32) * np.float32(scale)).astype(mxu)
        k = k_ref[0].astype(mxu)
        v = v_ref[0].astype(mxu)
        do = do_ref[0].astype(mxu)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_sc[:] = dq_sc[:] + jax.lax.dot_general(
            ds.astype(mxu), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _finish():
        dq_ref[0] = (dq_sc[:] * np.float32(scale)).astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# backward: dk/dv pass (grid over k blocks x q blocks, dk/dv scratch)
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, o_ref,
                    *rest, scale, causal, block_q, block_k, nq, mxu,
                    emit_dq=False):
    """Shared dk/dv (+ optionally dq) backward body, grid (BH, nk, nq)
    with the q sweep innermost. dk/dv accumulate in scratch over the q
    sweep; with emit_dq each (ki, qj) writes that q block's dq directly —
    valid only when nk == 1 (each dq block visited once), which is how
    _bwd_dispatch routes it."""
    if emit_dq:
        dq_ref, dk_ref, dv_ref, dk_sc, dv_sc = rest
    else:
        dk_ref, dv_ref, dk_sc, dv_sc = rest
    ki = pl.program_id(1)
    qj = pl.program_id(2)

    @pl.when(qj == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc[:])
        dv_sc[:] = jnp.zeros_like(dv_sc[:])

    # causal: q blocks entirely above this k block contribute nothing
    should = (qj * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(should)
    def _step():
        q = (q_ref[0].astype(jnp.float32) * np.float32(scale)).astype(mxu)                                 # [bq, D]
        k = k_ref[0].astype(mxu)                 # [bk, D]
        v = v_ref[0].astype(mxu)
        do = do_ref[0].astype(mxu)
        lse = lse_ref[0][:, :1]
        # delta = rowsum(dO * O) computed in-kernel: avoids materialising
        # a [BH, T, LANE] f32 delta in HBM (ADVICE r1: 128x overhead for
        # per-row scalars)
        delta = jnp.sum(do_ref[0].astype(jnp.float32)
                        * o_ref[0].astype(jnp.float32), axis=1,
                        keepdims=True)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            rows = qj * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                              # [bq, bk]
        dv_sc[:] = dv_sc[:] + jax.lax.dot_general(
            p.astype(mxu), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_sc[:] = dk_sc[:] + jax.lax.dot_general(
            ds.astype(mxu), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if emit_dq:
            dq_ref[0] = (jax.lax.dot_general(
                ds.astype(mxu), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
                * np.float32(scale)).astype(dq_ref.dtype)

    if emit_dq:
        @pl.when(jnp.logical_not(should))
        def _masked_dq():
            dq_ref[0] = jnp.zeros_like(dq_ref[0])

    @pl.when(qj == nq - 1)
    def _finish():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)  # q already carries scale
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd(scale, causal, res, g):
    q3, k3, v3, o3, lse = res
    BH, T, D = q3.shape
    bq, bk = _bwd_block_sizes(T, D)
    nq, nk = T // bq, T // bk
    do3 = g
    # dq pass still consumes a precomputed delta (its blocks iterate k
    # inner, so per-block recompute there would repeat the same rowsum)
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (BH, T, LANE))

    kwargs = {}
    if pltpu is not None and not _interpret():
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, nk=nk, mxu=_mxu_dtype()),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bq, LANE), lambda b, i, j: (b, i, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bq, LANE), lambda b, i, j: (b, i, _I0),
                         memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, _I0),
                               memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q3.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)]
        if pltpu is not None else [],
        interpret=_interpret(),
        **kwargs,
    )(q3, k3, v3, do3, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, nq=nq, mxu=_mxu_dtype()),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, j, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, j, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bq, LANE), lambda b, i, j: (b, j, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, j, _I0),
                         memory_space=_VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, _I0),
                         memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ] if pltpu is not None else [],
        interpret=_interpret(),
        **kwargs,
    )(q3, k3, v3, do3, lse, o3)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash3(q3, k3, v3, scale, causal):
    o, _ = _fwd(q3, k3, v3, scale, causal)
    return o


def _flash3_fwd(q3, k3, v3, scale, causal):
    o, lse = _fwd(q3, k3, v3, scale, causal)
    return o, (q3, k3, v3, o, lse)


def _bwd_dispatch(scale, causal, res, g):
    """Fused single-pass backward when every q block sees a SINGLE k sweep
    (nk == 1, i.e. T <= the k block cap): its dq accumulation rides an
    aliased HBM buffer, which is only well-defined when no dq block is
    revisited across k iterations. Larger T uses the two-pass scheme."""
    T = res[0].shape[1]
    _, bk = _bwd_block_sizes(T, res[0].shape[2])
    if (T // bk) == 1 and os.environ.get("PT_FLASH_FUSED_BWD", "1") != "0":
        return _bwd_fused(scale, causal, res, g)
    return _bwd(scale, causal, res, g)


_flash3.defvjp(_flash3_fwd, _bwd_dispatch)


def flash_attention(q, k, v, causal=False, scale=None):
    """q/k/v: [B, T, H, D] (paddle layout) -> [B, T, H, D]."""
    B, T, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    # validate BOTH directions' blocks up front so a bad env override fails
    # here (where sdpa's fallback can catch it) rather than mid-backward
    bq, bk = _block_sizes(T, D)
    _bwd_block_sizes(T, D)
    if T % bq or T % bk:
        raise ValueError(f"flash_attention: seq len {T} must be a multiple "
                         f"of the block size {bq}")

    def to3(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(B * H, T, D)

    o3 = _flash3(to3(q), to3(k), to3(v), float(scale), bool(causal))
    return jnp.transpose(o3.reshape(B, H, T, D), (0, 2, 1, 3))


# ---------------------------------------------------------------------------
# fused single-pass backward (nk == 1 route): the shared kernel body with
# emit_dq — each (ki=0, qj) step computes dq for its q block directly, so
# the second score/probability recompute of the two-pass scheme (~30% of
# backward FLOPs) disappears.
# ---------------------------------------------------------------------------

def _bwd_fused(scale, causal, res, g):
    q3, k3, v3, o3, lse = res
    BH, T, D = q3.shape
    bq, bk = _bwd_block_sizes(T, D)
    nq, nk = T // bq, T // bk
    assert nk == 1, "fused backward requires a single k sweep"
    do3 = g      # delta is computed in-kernel from (do, o) blocks

    kwargs = {}
    if pltpu is not None and not _interpret():
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"))

    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, nq=nq, mxu=_mxu_dtype(),
                          emit_dq=True),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, j, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, j, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bq, LANE), lambda b, i, j: (b, j, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, j, _I0),
                         memory_space=_VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, j, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, _I0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, _I0),
                         memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, T, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ] if pltpu is not None else [],
        interpret=_interpret(),
        **kwargs,
    )(q3, k3, v3, do3, lse, o3)
    return dq, dk, dv
