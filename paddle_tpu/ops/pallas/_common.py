"""Shared helpers for the Pallas TPU kernels (flash_attention, fused_ce)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

try:  # pltpu only resolves on TPU builds; interpret mode covers CPU tests
    from jax.experimental.pallas import tpu as pltpu
    VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    VMEM = None

NEG_INF = np.float32(-1e30)
LANE = 128      # TPU lane width: per-row scalars ride a broadcast lane dim
I0 = np.int32(0)  # index-map zero pinned to i32 (x64 would make it i64)


def on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def interpret() -> bool:
    return not on_tpu()


def mxu_dtype():
    """MXU operand dtype follows jax_default_matmul_precision: 'highest'
    keeps f32 operands (tests, debugging); the TPU default streams bf16
    through the MXU at full rate (accumulation is always f32)."""
    prec = jax.config.jax_default_matmul_precision
    if prec in ("highest", "float32"):
        return jnp.float32
    return jnp.bfloat16
