"""Comparison / logical / bitwise ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, apply

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "equal_all", "allclose", "isclose", "logical_and",
    "logical_or", "logical_not", "logical_xor", "bitwise_and", "bitwise_or",
    "bitwise_not", "bitwise_xor", "is_empty", "is_tensor",
]


def _cmp(jfn):
    def op(x, y, name=None):
        return apply(jfn, x, y)
    return op


equal = _cmp(jnp.equal)
not_equal = _cmp(jnp.not_equal)
greater_than = _cmp(jnp.greater)
greater_equal = _cmp(jnp.greater_equal)
less_than = _cmp(jnp.less)
less_equal = _cmp(jnp.less_equal)
logical_and = _cmp(jnp.logical_and)
logical_or = _cmp(jnp.logical_or)
logical_xor = _cmp(jnp.logical_xor)
bitwise_and = _cmp(jnp.bitwise_and)
bitwise_or = _cmp(jnp.bitwise_or)
bitwise_xor = _cmp(jnp.bitwise_xor)


def logical_not(x, name=None):
    return apply(jnp.logical_not, x)


def bitwise_not(x, name=None):
    return apply(jnp.bitwise_not, x)


def equal_all(x, y, name=None):
    return apply(lambda a, b: jnp.array_equal(a, b), x, y)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                           equal_nan=equal_nan), x, y)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                          equal_nan=equal_nan), x, y)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
