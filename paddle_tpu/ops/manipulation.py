"""Shape/layout manipulation ops (reference: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

py_slice = builtins.slice  # the module defines a paddle-style `slice` op below

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor, apply

__all__ = [
    "reshape", "reshape_", "flatten", "squeeze", "squeeze_", "unsqueeze_", "scatter_", "unsqueeze", "transpose",
    "concat", "stack", "split", "chunk", "tile", "expand", "expand_as",
    "broadcast_to", "broadcast_shape", "flip", "reverse", "roll", "gather",
    "gather_nd", "scatter", "scatter_nd", "scatter_nd_add", "index_select",
    "index_add", "slice", "strided_slice", "unique", "unique_consecutive",
    "unbind", "cast", "pad", "repeat_interleave", "take_along_axis",
    "put_along_axis", "rot90", "unstack", "moveaxis", "swapaxes", "tensordot",
    "as_real", "as_complex", "view", "view_as", "crop", "tolist",
    "atleast_1d", "atleast_2d", "atleast_3d", "stride_check",
]


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    out = []
    for s in shape:
        if isinstance(s, Tensor):
            out.append(int(s.item()))
        elif isinstance(s, (int, np.integer)):
            out.append(int(s))
        else:
            # symbolic dims (jax.export shape polymorphism) pass through
            out.append(s)
    return tuple(out)


def reshape(x, shape, name=None):
    shape = _shape_arg(shape)
    return apply(lambda a: jnp.reshape(a, shape), x)


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._data = out._data
    x._node = out._node
    x._out_idx = out._out_idx
    return x


view = reshape


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, new_shape)
    return apply(f, x)


def squeeze(x, axis=None, name=None):
    def f(a):
        if axis is None:
            return jnp.squeeze(a)
        ax = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(i % a.ndim for i in ax if a.shape[i % a.ndim] == 1)
        return jnp.squeeze(a, axis=ax) if ax else a
    return apply(f, x)


def unsqueeze(x, axis, name=None):
    def f(a):
        ax = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = [int(i.item()) if isinstance(i, Tensor) else int(i) for i in ax]
        return jnp.expand_dims(a, axis=tuple(ax))
    return apply(f, x)


def transpose(x, perm=None, name=None):
    if perm is not None:
        perm = tuple(int(p) for p in perm)
    return apply(lambda a: jnp.transpose(a, perm), x)


def moveaxis(x, source, destination, name=None):
    return apply(lambda a: jnp.moveaxis(a, source, destination), x)


def swapaxes(x, axis0, axis1, name=None):
    return apply(lambda a: jnp.swapaxes(a, axis0, axis1), x)


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply(lambda *xs: jnp.concatenate(xs, axis=axis), *x, op_name="concat")


def stack(x, axis=0, name=None):
    return apply(lambda *xs: jnp.stack(xs, axis=axis), *x, op_name="stack")


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) if not isinstance(s, Tensor) else int(s.item())
                 for s in num_or_sections]
        residual = dim - sum(s for s in sizes if s >= 0)
        sizes = [residual if s < 0 else s for s in sizes]
    # int32 offsets: under x64 a python-int start index becomes an s64
    # constant, and the transposed dynamic_update_slice then mixes s64/s32
    # in the SPMD partitioner's offset arithmetic (verifier error when the
    # split sits inside a partitioned lax.scan body)
    offsets = [np.int32(o) for o in np.cumsum([0] + sizes[:-1])]

    def f(a):
        return tuple(jax.lax.dynamic_slice_in_dim(a, o, s, axis)
                     for o, s in zip(offsets, sizes))
    return list(apply(f, x, op_name="split"))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    n = x.shape[axis]

    def f(a):
        return tuple(jnp.squeeze(s, axis=axis)
                     for s in jnp.split(a, n, axis=axis))
    return list(apply(f, x, op_name="unbind"))


unstack = unbind


def tile(x, repeat_times, name=None):
    reps = _shape_arg(repeat_times)
    return apply(lambda a: jnp.tile(a, reps), x)


def expand(x, shape, name=None):
    shape = _shape_arg(shape)

    def f(a):
        tgt = list(shape)
        src = list(a.shape)
        # paddle expand: -1 keeps the original dim
        off = len(tgt) - len(src)
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = src[i - off]
        return jnp.broadcast_to(a, tuple(tgt))
    return apply(f, x)


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return apply(lambda a: jnp.broadcast_to(a, _shape_arg(shape)), x)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def flip(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply(lambda a: jnp.flip(a, axis=ax), x)


def reverse(x, axis, name=None):
    return flip(x, axis)


def roll(x, shifts, axis=None, name=None):
    return apply(lambda a: jnp.roll(a, shifts, axis=axis), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x)


def cast(x, dtype):
    d = dtype_mod.convert_dtype(dtype)
    return apply(lambda a: a.astype(d), x, op_name="cast")


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())

    def f(a, idx):
        return jnp.take(a, idx.reshape(-1) if idx.ndim > 1 else idx, axis=axis)
    return apply(f, x, index, op_name="gather")


def gather_nd(x, index, name=None):
    def f(a, idx):
        ndim = idx.shape[-1]
        idx_t = tuple(jnp.moveaxis(idx, -1, 0))
        return a[idx_t] if ndim == a.ndim else a[idx_t + (Ellipsis,)]
    return apply(f, x, index, op_name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def f(a, idx, upd):
        if overwrite:
            return a.at[idx].set(upd)
        # paddle semantics: zero destination rows then accumulate
        zeroed = a.at[idx].set(jnp.zeros_like(upd))
        return zeroed.at[idx].add(upd)
    return apply(f, x, index, updates, op_name="scatter")


def scatter_nd_add(x, index, updates, name=None):
    def f(a, idx, upd):
        idx_t = tuple(jnp.moveaxis(idx, -1, 0))
        return a.at[idx_t].add(upd)
    return apply(f, x, index, updates, op_name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros
    z = zeros(shape, dtype=updates.dtype)
    return scatter_nd_add(z, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply(lambda a, idx: jnp.take(a, idx, axis=axis), x, index,
                 op_name="index_select")


def index_add(x, index, axis, value, name=None):
    def f(a, idx, v):
        return jnp.moveaxis(jnp.moveaxis(a, axis, 0).at[idx].add(
            jnp.moveaxis(v, axis, 0)), 0, axis)
    return apply(f, x, index, value, op_name="index_add")


def slice(x, axes, starts, ends, name=None):
    def val(v):
        return int(v.item()) if isinstance(v, Tensor) else int(v)
    axes = [val(a) for a in axes]
    starts = [val(s) for s in starts]
    ends = [val(e) for e in ends]

    def f(a):
        index = [py_slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            index[ax] = py_slice(s, e)
        return a[tuple(index)]
    return apply(f, x, op_name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    def f(a):
        index = [py_slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            index[ax] = py_slice(s, e, st)
        return a[tuple(index)]
    return apply(f, x, op_name="strided_slice")


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    vals, idx, inv, cnt = np.unique(x.numpy(), return_index=True,
                                    return_inverse=True, return_counts=True,
                                    axis=axis)
    out = [Tensor(vals)]
    if return_index:
        out.append(Tensor(idx.astype(np.int64)))
    if return_inverse:
        out.append(Tensor(inv.astype(np.int64)))
    if return_counts:
        out.append(Tensor(cnt.astype(np.int64)))
    return out[0] if len(out) == 1 else tuple(out)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    a = x.numpy()
    if axis is None:
        a = a.reshape(-1)
    keep = np.ones(a.shape[0], dtype=bool)
    keep[1:] = np.any(a[1:] != a[:-1], axis=tuple(range(1, a.ndim))) if a.ndim > 1 \
        else a[1:] != a[:-1]
    vals = a[keep]
    out = [Tensor(vals)]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        out.append(Tensor(inv.astype(np.int64)))
    if return_counts:
        idx = np.flatnonzero(keep)
        cnt = np.diff(np.append(idx, a.shape[0]))
        out.append(Tensor(cnt.astype(np.int64)))
    return out[0] if len(out) == 1 else tuple(out)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]

    def f(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # paddle NCHW/NCL/NCDHW convention: pad applies to spatial dims,
            # listed from the last dim backwards in (before, after) pairs.
            n_spatial = len(pad) // 2
            widths = [(0, 0)] * (nd - n_spatial)
            spatial = [(pad[2 * i], pad[2 * i + 1]) for i in range(n_spatial)]
            if data_format.startswith("NC"):
                widths += spatial
            else:  # channels-last: spatial dims precede C
                widths = [(0, 0)] + spatial + [(0, 0)]
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, widths, mode=jmode, constant_values=value)
        return jnp.pad(a, widths, mode=jmode)
    return apply(f, x)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = repeats._data
        return apply(lambda a, r: jnp.repeat(a, r, axis=axis,
                                             total_repeat_length=int(reps.sum())),
                     x, repeats, op_name="repeat_interleave")
    return apply(lambda a: jnp.repeat(a, repeats, axis=axis), x)


def take_along_axis(arr, indices, axis, name=None):
    return apply(lambda a, i: jnp.take_along_axis(a, i, axis=axis), arr, indices,
                 op_name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def f(a, i, v):
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        idx = [jnp.arange(s).reshape([-1 if d == k else 1 for d in range(i.ndim)])
               for k, s in enumerate(i.shape)]
        idx[axis] = i
        if reduce == "assign":
            return a.at[tuple(idx)].set(v)
        if reduce == "add":
            return a.at[tuple(idx)].add(v)
        if reduce == "multiply":
            return a.at[tuple(idx)].multiply(v)
        raise ValueError(f"unknown reduce {reduce}")
    return apply(f, arr, indices, values, op_name="put_along_axis")


def tensordot(x, y, axes=2, name=None):
    return apply(lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


def as_real(x, name=None):
    def f(a):
        return jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1)
    return apply(f, x)


def as_complex(x, name=None):
    return apply(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x)


def crop(x, shape=None, offsets=None, name=None):
    shape = _shape_arg(shape)
    offsets = [0] * len(shape) if offsets is None else \
        [int(o.item()) if isinstance(o, Tensor) else int(o) for o in offsets]

    def f(a):
        index = tuple(py_slice(o, o + s) for o, s in zip(offsets, shape))
        return a[index]
    return apply(f, x)


def tolist(x):
    return x.tolist()


def atleast_1d(*xs):
    out = [apply(jnp.atleast_1d, x) for x in xs]
    return out[0] if len(out) == 1 else out


def atleast_2d(*xs):
    out = [apply(jnp.atleast_2d, x) for x in xs]
    return out[0] if len(out) == 1 else out


def atleast_3d(*xs):
    out = [apply(jnp.atleast_3d, x) for x in xs]
    return out[0] if len(out) == 1 else out


def stride_check(*_a, **_k):
    raise NotImplementedError("strides are not observable under XLA")


def _inplace_from(x, out):
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    return x


def squeeze_(x, axis=None, name=None):
    return _inplace_from(x, squeeze(x, axis))


def unsqueeze_(x, axis, name=None):
    return _inplace_from(x, unsqueeze(x, axis))


def scatter_(x, index, updates, overwrite=True, name=None):
    return _inplace_from(x, scatter(x, index, updates, overwrite=overwrite))
