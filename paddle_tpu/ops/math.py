"""Elementwise + reduction math ops (reference: python/paddle/tensor/math.py,
kernels in paddle/fluid/operators/elementwise/ and reduce_ops/)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor, apply

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "remainder",
    "mod", "floor_mod", "pow", "sqrt", "rsqrt", "square", "exp", "expm1",
    "log", "log2", "log10", "log1p", "abs", "neg", "sign", "floor", "ceil",
    "round", "trunc", "sin", "cos", "tan", "asin", "acos", "atan", "sinh",
    "cosh", "tanh", "tanh_", "addmm", "all", "any", "asinh", "acosh", "atanh", "reciprocal", "clip",
    "maximum", "minimum", "fmax", "fmin", "max", "min", "amax", "amin",
    "sum", "nansum", "mean", "nanmean", "prod", "cumsum", "cumprod",
    "logsumexp", "logcumsumexp", "add_n", "scale", "stanh", "erf", "erfinv",
    "lgamma", "digamma", "atan2", "isnan", "isinf", "isfinite", "nan_to_num",
    "kron", "inner", "outer", "trace", "increment", "multiplex", "lerp",
    "rad2deg", "deg2rad", "gcd", "lcm", "angle", "conj", "real", "imag",
    "heaviside", "frac", "sgn", "diff", "count_nonzero",
]


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _unary(jfn):
    def op(x, name=None):
        return apply(jfn, x)
    return op


def _binary(jfn):
    def op(x, y, name=None):
        return apply(jfn, x, y)
    return op


add = _binary(jnp.add)
subtract = _binary(jnp.subtract)
multiply = _binary(jnp.multiply)
def _true_divide_f32(a, b):
    # int/int true division yields the default float dtype, not x64 float64
    out = jnp.true_divide(a, b)
    if out.dtype == jnp.float64 and not (
            jnp.issubdtype(jnp.result_type(a), jnp.floating)
            or jnp.issubdtype(jnp.result_type(b), jnp.floating)):
        out = out.astype(dtype_mod.get_default_dtype())
    return out


divide = _binary(_true_divide_f32)
floor_divide = _binary(jnp.floor_divide)
remainder = _binary(jnp.remainder)
mod = remainder
floor_mod = remainder
pow = _binary(jnp.power)
maximum = _binary(jnp.maximum)
minimum = _binary(jnp.minimum)
fmax = _binary(jnp.fmax)
fmin = _binary(jnp.fmin)
atan2 = _binary(jnp.arctan2)
kron = _binary(jnp.kron)
heaviside = _binary(jnp.heaviside)
gcd = _binary(jnp.gcd)
lcm = _binary(jnp.lcm)

sqrt = _unary(jnp.sqrt)
rsqrt = _unary(jax.lax.rsqrt)
square = _unary(jnp.square)
exp = _unary(jnp.exp)
expm1 = _unary(jnp.expm1)
log = _unary(jnp.log)
log2 = _unary(jnp.log2)
log10 = _unary(jnp.log10)
log1p = _unary(jnp.log1p)
abs = _unary(jnp.abs)
neg = _unary(jnp.negative)
sign = _unary(jnp.sign)
floor = _unary(jnp.floor)
ceil = _unary(jnp.ceil)
round = _unary(jnp.round)
trunc = _unary(jnp.trunc)
sin = _unary(jnp.sin)
cos = _unary(jnp.cos)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
acos = _unary(jnp.arccos)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
cosh = _unary(jnp.cosh)
tanh = _unary(jnp.tanh)
asinh = _unary(jnp.arcsinh)
acosh = _unary(jnp.arccosh)
atanh = _unary(jnp.arctanh)
reciprocal = _unary(jnp.reciprocal)
erf = _unary(jax.lax.erf)
erfinv = _unary(jax.lax.erf_inv)
lgamma = _unary(jax.lax.lgamma)
digamma = _unary(jax.lax.digamma)
isnan = _unary(jnp.isnan)
isinf = _unary(jnp.isinf)
isfinite = _unary(jnp.isfinite)
angle = _unary(jnp.angle)
conj = _unary(jnp.conj)
real = _unary(jnp.real)
imag = _unary(jnp.imag)
rad2deg = _unary(jnp.rad2deg)
deg2rad = _unary(jnp.deg2rad)
frac = _unary(lambda a: a - jnp.trunc(a))
sgn = _unary(jnp.sign)


def clip(x, min=None, max=None, name=None):
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return apply(lambda a: jnp.clip(a, lo, hi), x)


def max(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.max(a, axis=_axis(axis), keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.min(a, axis=_axis(axis), keepdims=keepdim), x)


amax = max
amin = min


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    from ..core.dtype import convert_dtype
    dt = convert_dtype(dtype)

    def f(a):
        out = jnp.sum(a, axis=_axis(axis), keepdims=keepdim, dtype=dt)
        if dt is None and jnp.issubdtype(a.dtype, jnp.bool_):
            out = out.astype(jnp.int64)
        return out
    return apply(f, x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nansum(a, axis=_axis(axis), keepdims=keepdim), x)


def mean(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.mean(a, axis=_axis(axis), keepdims=keepdim), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nanmean(a, axis=_axis(axis), keepdims=keepdim), x)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return apply(lambda a: jnp.prod(a, axis=_axis(axis), keepdims=keepdim), x)


def cumsum(x, axis=None, dtype=None, name=None):
    def f(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1))
        return jnp.cumsum(a, axis=int(axis))
    return apply(f, x)


def cumprod(x, dim=None, dtype=None, name=None):
    return apply(lambda a: jnp.cumprod(a, axis=dim), x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jax.scipy.special.logsumexp(a, axis=_axis(axis), keepdims=keepdim), x)


def logcumsumexp(x, axis=None, name=None):
    def f(a):
        if axis is None:
            b = a.reshape(-1)
            ax = 0
        else:
            b, ax = a, int(axis)
        m = jnp.max(b, axis=ax, keepdims=True)
        return jnp.log(jnp.cumsum(jnp.exp(b - m), axis=ax)) + m
    return apply(f, x)


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    return apply(lambda *xs: sum_arrays(xs), *inputs, op_name="add_n")


def sum_arrays(xs):
    out = xs[0]
    for a in xs[1:]:
        out = out + a
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scale.item() if isinstance(scale, Tensor) else scale

    def f(a):
        out = a * s + bias if bias_after_scale else (a + bias) * s
        return out
    out = apply(f, x)
    if act is not None:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(lambda a: scale_b * jnp.tanh(scale_a * a), x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), x)


def inner(x, y, name=None):
    return apply(lambda a, b: jnp.inner(a, b), x, y)


def outer(x, y, name=None):
    return apply(lambda a, b: jnp.outer(a, b), x, y)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), x)


def increment(x, value=1.0, name=None):
    x.set_value(x._data + value)
    return x


def multiplex(inputs, index, name=None):
    def f(idx, *xs):
        stacked = jnp.stack(xs, axis=0)          # (n, batch, ...)
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx.reshape(-1), rows]
    return apply(f, index, *inputs, op_name="multiplex")


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply(lambda a, b, w: a + w * (b - a), x, y, weight)
    return apply(lambda a, b: a + weight * (b - a), x, y)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [x]
    if prepend is not None:
        args.append(prepend)
    if append is not None:
        args.append(append)

    def f(a, *rest):
        pre = rest[0] if prepend is not None else None
        app = rest[-1] if append is not None else None
        return jnp.diff(a, n=n, axis=axis, prepend=pre, append=app)
    return apply(f, *args, op_name="diff")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.count_nonzero(a, axis=_axis(axis), keepdims=keepdim).astype(jnp.int64), x)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) (reference tensor/math.py addmm)."""
    return apply(lambda i, a, b: beta * i + alpha * (a @ b), input, x, y,
                 op_name="addmm")


def all(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.all(a, axis=_axis(axis), keepdims=keepdim),
                 x, op_name="all")


def any(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.any(a, axis=_axis(axis), keepdims=keepdim),
                 x, op_name="any")


def tanh_(x, name=None):
    """In-place surface over tanh (reference inplace-op pair tanh_)."""
    from .manipulation import _inplace_from
    return _inplace_from(x, tanh(x))
