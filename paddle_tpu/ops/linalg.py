"""Linear-algebra ops (reference: python/paddle/tensor/linalg.py; kernels in
paddle/fluid/operators/{matmul_op.*,math/blas.h}). Matmuls feed the MXU: we
keep them batched and let `tpu_matmul_precision` control lax precision."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.flags import get_flags
from ..core.tensor import Tensor, apply

__all__ = [
    "matmul", "mm", "bmm", "dot", "t", "norm", "dist", "cholesky", "inv", "inverse",
    "det", "slogdet", "svd", "qr", "eig", "eigh", "eigvals", "eigvalsh",
    "solve", "triangular_solve", "cholesky_solve", "matrix_power", "pinv",
    "cross", "histogram", "bincount", "mv", "matrix_rank", "lu", "lstsq",
    "multi_dot", "cov", "corrcoef", "rank",
]


def _precision():
    p = get_flags("tpu_matmul_precision")
    return None if p == "default" else p


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b, precision=_precision())
    return apply(f, x, y, op_name="matmul")


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return matmul(x, y)


def mv(x, vec, name=None):
    return apply(lambda a, b: jnp.matmul(a, b, precision=_precision()), x, vec)


def dot(x, y, name=None):
    def f(a, b):
        return jnp.sum(a * b, axis=-1)
    return apply(f, x, y, op_name="dot")


def t(input, name=None):
    return apply(lambda a: a.T if a.ndim >= 2 else a, input)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def f(a):
        if p == "fro" and (axis is None or isinstance(axis, (list, tuple))):
            ax = tuple(axis) if axis is not None else None
            return jnp.sqrt(jnp.sum(a * a, axis=ax, keepdims=keepdim))
        if p == "nuc":
            return jnp.sum(jnp.linalg.svd(a, compute_uv=False), axis=-1)
        pp = float("inf") if p == "inf" else (float("-inf") if p == "-inf" else p)
        if axis is None:
            return jnp.linalg.norm(a.reshape(-1), ord=pp, keepdims=keepdim)
        if isinstance(axis, (list, tuple)):
            return jnp.linalg.norm(a, ord=pp, axis=tuple(axis), keepdims=keepdim)
        if pp == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis, keepdims=keepdim)
        if pp == float("inf"):
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if pp == float("-inf"):
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** pp, axis=axis, keepdims=keepdim) ** (1.0 / pp)
    return apply(f, x, op_name="norm")


def dist(x, y, p=2, name=None):
    def f(a, b):
        d = (a - b).reshape(-1)
        if p == 0:
            return jnp.sum((d != 0).astype(d.dtype))
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)
    return apply(f, x, y, op_name="dist")


def cholesky(x, upper=False, name=None):
    def f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L
    return apply(f, x)


def inv(x, name=None):
    return apply(jnp.linalg.inv, x)


def det(x, name=None):
    return apply(jnp.linalg.det, x)


def slogdet(x, name=None):
    def f(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet], axis=0)
    return apply(f, x)


def svd(x, full_matrices=False, name=None):
    return apply(lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), x,
                 op_name="svd")


def qr(x, mode="reduced", name=None):
    out = apply(lambda a: tuple(jnp.linalg.qr(a, mode=mode)) if mode != "r"
                else (jnp.linalg.qr(a, mode="r"),), x, op_name="qr")
    return out if isinstance(out, tuple) and len(out) > 1 else out[0]


def eig(x, name=None):
    import numpy as np
    w, v = np.linalg.eig(x.numpy())
    return Tensor(w), Tensor(v)


def eigvals(x, name=None):
    import numpy as np
    return Tensor(np.linalg.eigvals(x.numpy()))


def eigh(x, UPLO="L", name=None):
    return apply(lambda a: tuple(jnp.linalg.eigh(a, symmetrize_input=False)), x,
                 op_name="eigh")


def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda a: jnp.linalg.eigvalsh(a), x)


def solve(x, y, name=None):
    return apply(jnp.linalg.solve, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply(f, x, y, op_name="triangular_solve")


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)
    return apply(f, x, y, op_name="cholesky_solve")


def matrix_power(x, n, name=None):
    return apply(lambda a: jnp.linalg.matrix_power(a, n), x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), x)


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply(f, x, y, op_name="cross")


def histogram(input, bins=100, min=0, max=0, name=None):
    a = input.numpy().reshape(-1)
    lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
    import numpy as np
    hist, _ = np.histogram(a, bins=bins, range=(lo, hi))
    return Tensor(hist.astype(np.int64))


def bincount(x, weights=None, minlength=0, name=None):
    if weights is None:
        return apply(lambda a: jnp.bincount(a, minlength=minlength,
                                            length=max(minlength, int(a.max()) + 1)), x)
    return apply(lambda a, w: jnp.bincount(a, w, minlength=minlength,
                                           length=max(minlength, int(a.max()) + 1)),
                 x, weights, op_name="bincount")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.matrix_rank(a, tol=tol), x)


def lu(x, pivot=True, get_infos=False, name=None):
    def f(a):
        lu_mat, piv = jax.scipy.linalg.lu_factor(a)
        # paddle returns LAPACK-style 1-based pivots (linalg.lu docs);
        # jax's lu_factor is 0-based
        return lu_mat, (piv + 1).astype(jnp.int32)
    lu_mat, piv = apply(f, x, op_name="lu")
    if get_infos:
        from .creation import zeros
        return lu_mat, piv, zeros([1], dtype="int32")
    return lu_mat, piv


def lstsq(x, y, rcond=None, driver=None, name=None):
    def f(a, b):
        sol, res, rk, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rk, sv
    return apply(f, x, y, op_name="lstsq")


def multi_dot(x, name=None):
    return apply(lambda *xs: jnp.linalg.multi_dot(xs), *x, op_name="multi_dot")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply(lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0), x)


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda a: jnp.corrcoef(a, rowvar=rowvar), x)


def rank(input, name=None):
    return Tensor(jnp.asarray(input.ndim, jnp.int32))


inverse = inv    # reference alias (tensor/linalg.py inverse)
