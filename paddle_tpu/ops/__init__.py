"""Functional tensor-op library.

TPU-native analog of /root/reference/python/paddle/tensor/ (~170 public
functions: math/linalg/manipulation/creation/random/search/stat/logic). The
reference routes each through a registered C++ op + CUDA kernel; here each op
is a jnp/lax expression dispatched through the eager tape (`core.tensor.apply`)
— XLA owns fusion and kernel selection, which subsumes the reference's
operators/math functor library (SURVEY.md rows 57/58).
"""
from ..core.tensor import Tensor, to_tensor, apply, no_grad, enable_grad, is_grad_enabled

from .creation import *       # noqa: F401,F403
from .math import *           # noqa: F401,F403
from .manipulation import *   # noqa: F401,F403
from .linalg import *         # noqa: F401,F403
from .logic import *          # noqa: F401,F403
from .random import *         # noqa: F401,F403
from .search import *         # noqa: F401,F403
from .stat import *           # noqa: F401,F403
from .einsum import einsum    # noqa: F401

from . import _bind           # noqa: F401  (attaches Tensor methods/dunders)
