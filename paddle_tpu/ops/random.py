"""Random ops (reference: python/paddle/tensor/random.py; generator.cc RNG).

Eager calls draw fresh keys from the global splittable generator
(core.random). Inside jit-traced code use the `key=` argument to stay
functional — the fit-loop fast path threads keys explicitly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core import random as random_mod
from ..core.tensor import Tensor, apply

__all__ = [
    "rand", "randn", "randint", "randint_like", "randperm", "uniform",
    "uniform_", "normal", "standard_normal", "gaussian", "bernoulli", "multinomial",
    "poisson", "exponential_",
]


def _key(key):
    return key if key is not None else random_mod.next_key()


def _dt(dtype):
    d = dtype_mod.convert_dtype(dtype)
    return d if d is not None else dtype_mod.get_default_dtype()


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None, key=None):
    k = jax.random.key(seed) if seed else _key(key)
    return Tensor(jax.random.uniform(k, _shape(shape), _dt(dtype), min, max))


def rand(shape, dtype=None, name=None, key=None):
    return uniform(shape, dtype, 0.0, 1.0, key=key)


def randn(shape, dtype=None, name=None, key=None):
    return Tensor(jax.random.normal(_key(key), _shape(shape), _dt(dtype)))


standard_normal = randn


def normal(mean=0.0, std=1.0, shape=None, name=None, key=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(_key(key), shp,
                                        dtype_mod.get_default_dtype()) * s + m)
    return Tensor(jax.random.normal(_key(key), _shape(shape),
                                    dtype_mod.get_default_dtype()) * std + mean)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None, key=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_key(key), _shape(shape), low, high,
                                     dtype_mod.convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None, key=None):
    d = dtype_mod.convert_dtype(dtype) or x.dtype
    if high is None:
        low, high = 0, low
    out = jax.random.randint(_key(key), tuple(x.shape), low, high, jnp.int64)
    return Tensor(out.astype(d))


def randperm(n, dtype="int64", name=None, key=None):
    return Tensor(jax.random.permutation(_key(key), n).astype(
        dtype_mod.convert_dtype(dtype)))


def bernoulli(x, name=None, key=None):
    k = _key(key)
    return apply(lambda a: jax.random.bernoulli(k, a).astype(a.dtype), x,
                 op_name="bernoulli")


def multinomial(x, num_samples=1, replacement=False, name=None, key=None):
    k = _key(key)

    def f(a):
        logits = jnp.log(jnp.maximum(a, 1e-30))
        if replacement:
            return jax.random.categorical(k, logits, axis=-1,
                                          shape=a.shape[:-1] + (num_samples,))
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(k, a.shape, dtype=logits.dtype)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx
    return Tensor(f(x._data).astype(jnp.int64))


def poisson(x, name=None, key=None):
    k = _key(key)
    return apply(lambda a: jax.random.poisson(k, a).astype(a.dtype), x,
                 op_name="poisson")


def exponential_(x, lam=1.0, name=None, key=None):
    out = jax.random.exponential(_key(key), tuple(x.shape), x.dtype) / lam
    x.set_value(out)
    return x


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None, key=None):
    k = jax.random.key(seed) if seed else _key(key)
    x.set_value(jax.random.uniform(k, tuple(x.shape), x.dtype, min, max))
    return x


def gaussian(shape, mean=0.0, std=1.0, dtype=None, name=None, key=None):
    """reference tensor/random.py gaussian: N(mean, std) samples."""
    out = standard_normal(shape, dtype=dtype, key=key)
    return apply(lambda a: a * std + mean, out, op_name="gaussian")
