"""einsum (reference: python/paddle/tensor/einsum.py) — direct jnp lowering,
MXU-friendly via dot_general."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import apply


def einsum(equation, *operands):
    return apply(lambda *xs: jnp.einsum(equation, *xs), *operands,
                 op_name="einsum")
