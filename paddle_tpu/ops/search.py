"""Search / sort / index ops (reference: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor, apply

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "where", "nonzero",
    "masked_select", "index_sample", "searchsorted", "kthvalue", "mode",
]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = dtype_mod.convert_dtype(dtype)

    def f(a):
        out = jnp.argmax(a.reshape(-1) if axis is None else a,
                         axis=None if axis is None else int(axis),
                         keepdims=keepdim if axis is not None else False)
        return out.astype(d)
    return apply(f, x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = dtype_mod.convert_dtype(dtype)

    def f(a):
        out = jnp.argmin(a.reshape(-1) if axis is None else a,
                         axis=None if axis is None else int(axis),
                         keepdims=keepdim if axis is not None else False)
        return out.astype(d)
    return apply(f, x)


def argsort(x, axis=-1, descending=False, name=None):
    def f(a):
        idx = jnp.argsort(a, axis=axis, descending=descending)
        return idx.astype(jnp.int64)
    return apply(f, x)


def sort(x, axis=-1, descending=False, name=None):
    def f(a):
        out = jnp.sort(a, axis=axis, descending=descending)
        return out
    return apply(f, x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())

    def f(a):
        ax = -1 if axis is None else int(axis)
        moved = jnp.moveaxis(a, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(moved, k)
        else:
            vals, idx = jax.lax.top_k(-moved, k)
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx, -1, ax).astype(jnp.int64))
    return apply(f, x, op_name="topk")


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply(lambda c, a, b: jnp.where(c, a, b), condition, x, y,
                 op_name="where")


def nonzero(x, as_tuple=False):
    idx = np.nonzero(x.numpy())
    if as_tuple:
        return tuple(Tensor(i.astype(np.int64).reshape(-1, 1)) for i in idx)
    return Tensor(np.stack(idx, axis=1).astype(np.int64))


def masked_select(x, mask, name=None):
    return Tensor(x.numpy()[np.asarray(mask.numpy(), bool)])


def index_sample(x, index, name=None):
    def f(a, idx):
        rows = jnp.arange(a.shape[0])[:, None]
        return a[rows, idx]
    return apply(f, x, index, op_name="index_sample")


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def f(seq, v):
        side = "right" if right else "left"
        if seq.ndim == 1:
            out = jnp.searchsorted(seq, v, side=side)
        else:
            out = jax.vmap(lambda s, vv: jnp.searchsorted(s, vv, side=side))(
                seq.reshape(-1, seq.shape[-1]), v.reshape(-1, v.shape[-1])
            ).reshape(v.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return apply(f, sorted_sequence, values, op_name="searchsorted")


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        ax = int(axis)
        vals = jnp.sort(a, axis=ax)
        idxs = jnp.argsort(a, axis=ax)
        v = jnp.take(vals, k - 1, axis=ax)
        i = jnp.take(idxs, k - 1, axis=ax)
        if keepdim:
            v = jnp.expand_dims(v, ax)
            i = jnp.expand_dims(i, ax)
        return v, i.astype(jnp.int64)
    return apply(f, x, op_name="kthvalue")


def mode(x, axis=-1, keepdim=False, name=None):
    a = x.numpy()
    from scipy import stats  # available via jax's scipy dep

    m = stats.mode(a, axis=axis, keepdims=keepdim)
    return Tensor(m.mode.astype(a.dtype)), Tensor(np.asarray(m.count))
