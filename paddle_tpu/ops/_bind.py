"""Attach the op library as Tensor methods + arithmetic dunders.

Analog of the reference's monkey_patch_varbase/monkey_patch_math_varbase
(python/paddle/fluid/dygraph/math_op_patch.py): every public tensor function
whose first argument is a tensor becomes a method.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from . import (creation, einsum, linalg, logic, manipulation, math, random,
               search, stat)

_METHOD_SOURCES = [math, manipulation, linalg, logic, search, stat, creation,
                   random]

_SKIP = {
    "zeros", "ones", "full", "arange", "linspace", "logspace", "eye", "empty",
    "meshgrid", "assign", "rand", "randn", "randint", "randperm", "uniform",
    "normal", "standard_normal", "scatter_nd", "is_tensor", "broadcast_shape",
    "stride_check",
}

for mod in _METHOD_SOURCES:
    for name in getattr(mod, "__all__", []):
        if name in _SKIP or hasattr(Tensor, name):
            continue
        fn = getattr(mod, name)
        if callable(fn):
            setattr(Tensor, name, fn)



def _binary_dunder(fn, reverse=False):
    if reverse:
        def op(self, other):
            return fn(other, self)
    else:
        def op(self, other):
            return fn(self, other)
    return op


Tensor.__add__ = _binary_dunder(math.add)
Tensor.__radd__ = _binary_dunder(math.add, True)
Tensor.__sub__ = _binary_dunder(math.subtract)
Tensor.__rsub__ = _binary_dunder(math.subtract, True)
Tensor.__mul__ = _binary_dunder(math.multiply)
Tensor.__rmul__ = _binary_dunder(math.multiply, True)
Tensor.__truediv__ = _binary_dunder(math.divide)
Tensor.__rtruediv__ = _binary_dunder(math.divide, True)
Tensor.__floordiv__ = _binary_dunder(math.floor_divide)
Tensor.__rfloordiv__ = _binary_dunder(math.floor_divide, True)
Tensor.__mod__ = _binary_dunder(math.remainder)
Tensor.__rmod__ = _binary_dunder(math.remainder, True)
Tensor.__pow__ = _binary_dunder(math.pow)
Tensor.__rpow__ = _binary_dunder(math.pow, True)
Tensor.__matmul__ = _binary_dunder(linalg.matmul)
Tensor.__rmatmul__ = _binary_dunder(linalg.matmul, True)
Tensor.__neg__ = lambda self: math.neg(self)
Tensor.__abs__ = lambda self: math.abs(self)
Tensor.__invert__ = lambda self: logic.logical_not(self) \
    if self.dtype == bool else logic.bitwise_not(self)
Tensor.__and__ = _binary_dunder(logic.bitwise_and)
Tensor.__or__ = _binary_dunder(logic.bitwise_or)
Tensor.__xor__ = _binary_dunder(logic.bitwise_xor)
Tensor.__eq__ = _binary_dunder(logic.equal)
Tensor.__ne__ = _binary_dunder(logic.not_equal)
Tensor.__lt__ = _binary_dunder(logic.less_than)
Tensor.__le__ = _binary_dunder(logic.less_equal)
Tensor.__gt__ = _binary_dunder(logic.greater_than)
Tensor.__ge__ = _binary_dunder(logic.greater_equal)
Tensor.__hash__ = object.__hash__  # __eq__ override would otherwise drop it
