"""paddle.incubate — experimental APIs kept at reference paths
(python/paddle/incubate/__init__.py)."""
from . import optimizer  # noqa: F401

__all__ = ["optimizer"]
