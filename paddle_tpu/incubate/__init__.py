"""paddle.incubate — experimental APIs kept at reference paths
(python/paddle/incubate/__init__.py)."""
from . import optimizer  # noqa: F401

__all__ = ["optimizer"]


class LayerHelper:
    """Thin fluid LayerHelper analog (reference fluid/layer_helper.py):
    eager layers own their parameters directly, so the helper only
    carries the naming/creation conveniences porting code touches."""

    def __init__(self, layer_type, **kwargs):
        self.layer_type = layer_type
        self.kwargs = kwargs

    def create_parameter(self, attr=None, shape=None, dtype="float32",
                         is_bias=False, default_initializer=None):
        from ..legacy_alias import create_parameter as _cp
        return _cp(shape, dtype, attr=attr, is_bias=is_bias,
                   default_initializer=default_initializer)

    def create_variable_for_type_inference(self, dtype="float32"):
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        return Tensor(jnp.zeros([], jnp.dtype(dtype)))


from ..io import reader_compat as reader  # noqa: F401,E402
