"""paddle.incubate.optimizer — LookAhead and ModelAverage.

Reference: python/paddle/incubate/optimizer/lookahead.py:26 (LookAhead,
arXiv:1907.08610 slow/fast weights) and modelaverage.py:27 + the
average_accumulates kernel fluid/operators/average_accumulates_op.h
(3-sum sliding-window average with the 16384-step precision rotation).

TPU-native: both are pure pytree transforms. LookAhead wraps any inner
optimizer — eager `step()` and the compiler's functional path both work
(the k-boundary merge is a jnp.where, so the jitted train step stays a
single traced program). ModelAverage is an eval-time tool: `step()`
accumulates, `apply()`/`restore()` swap the averaged weights in and out.
"""
from __future__ import annotations

import contextlib
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ...core.autograd import no_grad
from ...core.errors import InvalidArgumentError, enforce
from ...optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    """slow += alpha * (fast - slow); fast = slow — every k inner steps
    (reference lookahead.py:26)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        enforce(isinstance(inner_optimizer, Optimizer),
                "inner_optimizer must be a paddle optimizer",
                InvalidArgumentError)
        enforce(0.0 <= alpha <= 1.0, "alpha must be in [0, 1]",
                InvalidArgumentError)
        enforce(int(k) >= 1, "k must be a positive integer",
                InvalidArgumentError)
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._parameter_list = inner_optimizer._parameter_list
        self._grad_clip = None
        self._slow: Dict[int, jax.Array] = {}
        self._k_count = 0

    # lr surface delegates to the inner optimizer
    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def set_lr(self, v):
        return self.inner_optimizer.set_lr(v)

    @property
    def _lr_scheduler(self):
        return self.inner_optimizer._lr_scheduler

    def _fast_of(self, p):
        """The fp32 master weight when the inner optimizer keeps one
        (multi_precision), else the param itself — the slow/fast merge
        must read and WRITE the master, or the next inner step would
        overwrite the merge from the stale master copy."""
        return self.inner_optimizer._master.get(id(p), p._data)

    @no_grad()
    def step(self):
        params = self._parameter_list
        enforce(params is not None,
                "LookAhead needs the inner optimizer constructed with "
                "parameters=model.parameters()", InvalidArgumentError)
        for p in params:
            if id(p) not in self._slow:
                self._slow[id(p)] = self._fast_of(p)  # cycle start point
        self.inner_optimizer.step()
        self._k_count += 1
        if self._k_count % self.k == 0:
            for p in params:
                slow = self._slow[id(p)]
                slow = slow + self.alpha * (self._fast_of(p) - slow)
                self._slow[id(p)] = slow
                if id(p) in self.inner_optimizer._master:
                    self.inner_optimizer._master[id(p)] = slow
                p._data = slow.astype(p._data.dtype)

    minimize_step = step

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        return None, None

    # -- functional pytree path (fleet-compiled steps) ---------------------
    def functional_init(self, params):
        # slow starts as a COPY: jitted steps donate both params and opt
        # state, and aliased buffers would be donated twice
        return {
            "__lookahead__": {
                "slow": {k: jnp.copy(v) for k, v in params.items()},
                "step": jnp.zeros((), jnp.int32)},
            **self.inner_optimizer.functional_init(params),
        }

    def functional_update(self, params, grads, opt_state, lr=None):
        la = opt_state["__lookahead__"]
        inner_state = {k: v for k, v in opt_state.items()
                       if k != "__lookahead__"}
        fast, new_inner = self.inner_optimizer.functional_update(
            params, grads, inner_state, lr=lr)
        step = la["step"] + 1
        sync = (step % self.k) == 0
        new_slow, new_fast = {}, {}
        for k, f in fast.items():
            s = la["slow"][k]
            merged = s + self.alpha * (f - s)
            new_slow[k] = jnp.where(sync, merged, s)
            new_fast[k] = jnp.where(sync, merged.astype(f.dtype), f)
        new_inner["__lookahead__"] = {"slow": new_slow, "step": step}
        return new_fast, new_inner

    def collect_param_regularizers(self, layer):
        self.inner_optimizer.collect_param_regularizers(layer)

    def state_dict(self):
        out = self.inner_optimizer.state_dict()
        out["__lookahead_k_count__"] = self._k_count
        # slow weights are accumulator state: resuming mid-cycle without
        # them would re-anchor the next merge at the current fast point
        if self._parameter_list:
            for p in self._parameter_list:
                if id(p) in self._slow:
                    out[f"__lookahead_slow__{p.name}"] = \
                        np.asarray(self._slow[id(p)])
        return out

    def set_state_dict(self, state_dict):
        state_dict = dict(state_dict)
        self._k_count = int(state_dict.pop("__lookahead_k_count__", 0))
        if self._parameter_list:
            for p in self._parameter_list:
                v = state_dict.pop(f"__lookahead_slow__{p.name}", None)
                if v is not None:
                    self._slow[id(p)] = jnp.asarray(v)
        self.inner_optimizer.set_state_dict(state_dict)


class ModelAverage(Optimizer):
    """Sliding-window parameter average (reference modelaverage.py:27;
    window math = average_accumulates_op.h): `step()` accumulates after
    each optimizer update, `apply()` swaps the averaged weights in for
    evaluation, `restore()` puts the live weights back."""

    _MAX_NUM_ACCUMULATES = 16384   # precision rotation, matches the kernel

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        enforce(min_average_window <= max_average_window,
                "min_average_window must be <= max_average_window",
                InvalidArgumentError)
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._parameter_list = list(parameters) if parameters is not None \
            else None
        self._grad_clip = None
        self._acc: Dict[int, dict] = {}
        self._restore_buf: Dict[int, jax.Array] = {}
        self._applied = False

    def _acc_of(self, p):
        a = self._acc.get(id(p))
        if a is None:
            z = jnp.zeros_like(p._data)
            a = {"sum_1": z, "sum_2": z, "sum_3": z,
                 "num_accumulates": 0, "old_num_accumulates": 0,
                 "num_updates": 0}
            self._acc[id(p)] = a
        return a

    @no_grad()
    def step(self):
        enforce(self._parameter_list is not None,
                "ModelAverage needs parameters=model.parameters()",
                InvalidArgumentError)
        enforce(not self._applied,
                "ModelAverage.step() inside apply() — restore() first",
                InvalidArgumentError)
        for p in self._parameter_list:
            a = self._acc_of(p)
            a["num_updates"] += 1
            a["num_accumulates"] += 1
            a["sum_1"] = a["sum_1"] + p._data
            if a["num_updates"] % self._MAX_NUM_ACCUMULATES == 0:
                a["sum_2"] = a["sum_2"] + a["sum_1"]
                a["sum_1"] = jnp.zeros_like(a["sum_1"])
            if (a["num_accumulates"] >= self.min_average_window
                    and a["num_accumulates"] >= min(
                        self.max_average_window,
                        a["num_updates"] * self.average_window)):
                a["sum_3"] = a["sum_1"] + a["sum_2"]
                a["sum_1"] = jnp.zeros_like(a["sum_1"])
                a["sum_2"] = jnp.zeros_like(a["sum_2"])
                a["old_num_accumulates"] = a["num_accumulates"]
                a["num_accumulates"] = 0

    minimize_step = step

    def _average_of(self, p):
        a = self._acc_of(p)
        total = a["num_accumulates"] + a["old_num_accumulates"]
        if total == 0:
            return p._data
        s = a["sum_1"] + a["sum_2"] + a["sum_3"]
        return (s / float(total)).astype(p._data.dtype)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        """Context manager: inside, parameters hold their windowed
        average (reference :374). need_restore=False leaves the averaged
        weights in place on exit (pair with an explicit restore())."""
        enforce(not self._applied, "apply() is not reentrant",
                InvalidArgumentError)
        enforce(self._parameter_list is not None,
                "ModelAverage needs parameters=model.parameters()",
                InvalidArgumentError)
        for p in self._parameter_list:
            self._restore_buf[id(p)] = p._data
            p._data = self._average_of(p)
        self._applied = True
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        """Undo apply() (reference :430)."""
        if not self._applied:
            return
        for p in self._parameter_list:
            buf = self._restore_buf.pop(id(p), None)
            if buf is not None:
                p._data = buf
        self._applied = False

    def clear_grad(self, set_to_zero=False):
        pass

    clear_gradients = clear_grad
