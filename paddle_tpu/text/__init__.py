"""paddle.text — NLP datasets + sequence decode utilities.

Reference: python/paddle/text/datasets/ (Conll05st, Imdb, Imikolov,
Movielens, UCIHousing, WMT14, WMT16 — all Dataset subclasses whose
constructors download a corpus and build vocabularies).

TPU-native build runs with zero egress, so each dataset keeps the
reference class name and sample layout but sources from (a) a local
`data_file` in a simple documented format, or (b) `mode='synthetic'`
(deterministic generated corpora) so pipelines/tests run hermetically.
The download machinery (paddle.dataset.common.download) is intentionally
absent. viterbi_decode/ViterbiDecoder give the CRF decode op of the
later reference surface (lod-free: dense [B, T, N] emissions).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ..io.dataset import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Movielens", "WMT14", "WMT16",
           "Conll05st", "build_vocab", "viterbi_decode", "ViterbiDecoder"]


def build_vocab(corpus, min_freq=1, specials=("<pad>", "<unk>")):
    """token -> id map from an iterable of token lists."""
    freq: Dict[str, int] = {}
    for tokens in corpus:
        for t in tokens:
            freq[t] = freq.get(t, 0) + 1
    vocab = {s: i for i, s in enumerate(specials)}
    for t, c in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0])):
        if c >= min_freq and t not in vocab:
            vocab[t] = len(vocab)
    return vocab


def _synth_tokens(rng, n_docs, vocab_size, doc_len):
    return [[f"w{int(i)}" for i in
             rng.integers(2, vocab_size, rng.integers(5, doc_len))]
            for _ in range(n_docs)]


class Imdb(Dataset):
    """Sentiment classification: sample = (ids int64 [T], label int64).
    data_file format: one example per line, `label<TAB>space-joined text`
    (reference reads the aclImdb tar; same sample contract)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 1, vocab: Optional[dict] = None,
                 n_synthetic: int = 256):
        # cutoff = vocab frequency threshold (reference build_dict cutoff;
        # default 1 here instead of 150 because local/synthetic corpora
        # are tiny)
        docs, labels = [], []
        if data_file and os.path.exists(data_file):
            with open(data_file) as f:
                for line in f:
                    lab, _, text = line.rstrip("\n").partition("\t")
                    docs.append(text.split())
                    labels.append(int(lab))
        else:
            rng = np.random.default_rng(0 if mode == "train" else 1)
            docs = _synth_tokens(rng, n_synthetic, 200, 40)
            # synthetic labels correlate with a marker token so models
            # can actually learn something in tests
            labels = []
            for d in docs:
                pos = rng.random() < 0.5
                d.insert(0, "good" if pos else "bad")
                labels.append(int(pos))
        self.word_idx = vocab or build_vocab(docs, min_freq=cutoff)
        unk = self.word_idx.get("<unk>", 1)
        self.docs = [np.array([self.word_idx.get(t, unk) for t in d],
                              np.int64) for d in docs]
        self.labels = np.array(labels, np.int64)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]


class Imikolov(Dataset):
    """N-gram LM (PTB-style): sample = tuple of n int64 ids (context...,
    target). data_file: one sentence per line."""

    def __init__(self, data_file: Optional[str] = None, data_type="NGRAM",
                 window_size: int = 5, mode: str = "train",
                 min_word_freq: int = 1, n_synthetic: int = 128):
        if data_type not in ("NGRAM", "SEQ"):
            raise ValueError("data_type must be 'NGRAM' or 'SEQ'")
        if data_file and os.path.exists(data_file):
            with open(data_file) as f:
                sents = [l.split() for l in f if l.strip()]
        else:
            rng = np.random.default_rng(0 if mode == "train" else 1)
            sents = _synth_tokens(rng, n_synthetic, 100, 20)
        self.word_idx = build_vocab(sents, min_freq=min_word_freq,
                                    specials=("<s>", "<e>", "<unk>"))
        unk = self.word_idx["<unk>"]
        self.samples = []
        for s in sents:
            ids = [self.word_idx.get(t, unk) for t in s]
            ids = [self.word_idx["<s>"]] + ids + [self.word_idx["<e>"]]
            if data_type == "SEQ":
                # whole-sentence LM pairs (input, shifted target)
                self.samples.append((np.array(ids[:-1], np.int64),
                                     np.array(ids[1:], np.int64)))
            else:
                for i in range(len(ids) - window_size + 1):
                    self.samples.append(tuple(
                        np.int64(v) for v in ids[i:i + window_size]))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class UCIHousing(Dataset):
    """Regression: sample = (features f32 [13], price f32 [1]).
    data_file: whitespace-separated rows of 14 floats."""

    N_FEAT = 13

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 n_synthetic: int = 256):
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
        else:
            rng = np.random.default_rng(0 if mode == "train" else 1)
            x = rng.normal(size=(n_synthetic, self.N_FEAT))
            w = np.linspace(-1, 1, self.N_FEAT)
            y = x @ w + 0.1 * rng.normal(size=n_synthetic)
            raw = np.concatenate([x, y[:, None]], 1).astype(np.float32)
        mu, sig = raw[:, :-1].mean(0), raw[:, :-1].std(0) + 1e-8
        self.x = ((raw[:, :-1] - mu) / sig).astype(np.float32)
        self.y = raw[:, -1:].astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class Movielens(Dataset):
    """Rating prediction: sample = (user int64, movie int64, rating f32).
    data_file: `user<TAB>movie<TAB>rating` lines."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 n_users: int = 100, n_movies: int = 200,
                 n_synthetic: int = 1024):
        if data_file and os.path.exists(data_file):
            rows = np.loadtxt(data_file, delimiter="\t")
            self.users = rows[:, 0].astype(np.int64)
            self.movies = rows[:, 1].astype(np.int64)
            self.ratings = rows[:, 2].astype(np.float32)
        else:
            rng = np.random.default_rng(0 if mode == "train" else 1)
            self.users = rng.integers(0, n_users, n_synthetic)
            self.movies = rng.integers(0, n_movies, n_synthetic)
            u_bias = rng.normal(size=n_users)
            m_bias = rng.normal(size=n_movies)
            self.ratings = np.clip(
                3 + u_bias[self.users] + m_bias[self.movies]
                + 0.3 * rng.normal(size=n_synthetic), 1, 5).astype(
                    np.float32)

    def __len__(self):
        return len(self.users)

    def __getitem__(self, i):
        return self.users[i], self.movies[i], self.ratings[i]


class WMT14(Dataset):
    """Translation: sample = (src_ids int64, trg_ids int64 with <s>,
    trg_next int64 with </s>). data_file: `src sentence<TAB>trg sentence`."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 dict_size: int = 1000, n_synthetic: int = 128):
        pairs = []
        if data_file and os.path.exists(data_file):
            with open(data_file) as f:
                for line in f:
                    s, _, t = line.rstrip("\n").partition("\t")
                    pairs.append((s.split(), t.split()))
        else:
            rng = np.random.default_rng(0 if mode == "train" else 1)
            for _ in range(n_synthetic):
                n = int(rng.integers(3, 12))
                src = [f"s{int(i)}" for i in rng.integers(0, 50, n)]
                pairs.append((src, [t.replace("s", "t") for t in src]))
        self.src_idx = build_vocab((s for s, _ in pairs),
                                   specials=("<s>", "<e>", "<unk>"))
        self.trg_idx = build_vocab((t for _, t in pairs),
                                   specials=("<s>", "<e>", "<unk>"))
        su, tu = self.src_idx["<unk>"], self.trg_idx["<unk>"]
        self.samples = []
        for s, t in pairs:
            sid = np.array([self.src_idx.get(w, su) for w in s], np.int64)
            tid = [self.trg_idx.get(w, tu) for w in t]
            self.samples.append((
                sid,
                np.array([self.trg_idx["<s>"]] + tid, np.int64),
                np.array(tid + [self.trg_idx["<e>"]], np.int64)))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class WMT16(WMT14):
    """ACL2016 MMT translation set (reference
    python/paddle/text/datasets/wmt16.py:1: BPE-tokenized en<->de with
    <unk> replacement and per-language dicts). Same sample contract as
    the reference — (src_ids, trg_ids [<s> +], trg_next [+ <e>]) — over
    a local `data_file` (`src<TAB>trg` lines) or the synthetic corpus;
    src_dict_size/trg_dict_size of -1 keep the full vocabulary."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 src_dict_size: int = -1, trg_dict_size: int = -1,
                 lang: str = "en", n_synthetic: int = 128):
        super().__init__(data_file=data_file, mode=mode,
                         n_synthetic=n_synthetic)
        self.lang = lang
        self.src_dict_size = src_dict_size
        self.trg_dict_size = trg_dict_size
        for attr, cap in (("src_idx", src_dict_size),
                          ("trg_idx", trg_dict_size)):
            if cap and cap > 0:
                vocab = getattr(self, attr)
                unk = vocab["<unk>"]
                if cap <= unk:
                    raise ValueError(
                        f"WMT16 {attr[:3]}_dict_size={cap} would drop the "
                        f"specials (<s>/<e>/<unk> occupy ids 0..{unk}); "
                        f"use at least {unk + 1}")
                kept = {w: i for w, i in vocab.items() if i < cap}
                setattr(self, attr, kept)
                # remap dropped ids onto <unk> in the materialized samples
                col = 0 if attr == "src_idx" else 1
                fixed = []
                for smp in self.samples:
                    smp = list(smp)
                    if col == 0:
                        smp[0] = np.where(smp[0] < cap, smp[0], unk)
                    else:
                        smp[1] = np.where(smp[1] < cap, smp[1], unk)
                        smp[2] = np.where(smp[2] < cap, smp[2], unk)
                    fixed.append(tuple(smp))
                self.samples = fixed

    def get_dict(self, lang: str, reverse: bool = False):
        """Word dict for `lang` (reference wmt16.get_dict): the source
        language is self.lang; the other side is the target."""
        vocab = self.src_idx if lang == self.lang else self.trg_idx
        if reverse:
            return {i: w for w, i in vocab.items()}
        return dict(vocab)


class Conll05st(Dataset):
    """SRL-style tagging: sample = (word_ids int64 [T], pred_ids int64 [T],
    label_ids int64 [T]). data_file: `tokens<TAB>predicates<TAB>labels`
    (space-joined)."""

    N_LABELS = 9

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 n_synthetic: int = 128):
        self.samples = []
        if data_file and os.path.exists(data_file):
            with open(data_file) as f:
                rows = [l.rstrip("\n").split("\t") for l in f if l.strip()]
            toks = [r[0].split() for r in rows]
            self.word_idx = build_vocab(toks)
            unk = self.word_idx["<unk>"]
            for r, t in zip(rows, toks):
                w = np.array([self.word_idx.get(x, unk) for x in t],
                             np.int64)
                p = np.array([int(x) for x in r[1].split()], np.int64)
                l = np.array([int(x) for x in r[2].split()], np.int64)
                self.samples.append((w, p, l))
        else:
            rng = np.random.default_rng(0 if mode == "train" else 1)
            self.word_idx = {f"w{i}": i for i in range(100)}
            for _ in range(n_synthetic):
                T = int(rng.integers(5, 15))
                w = rng.integers(0, 100, T).astype(np.int64)
                p = (rng.random(T) < 0.2).astype(np.int64)
                l = rng.integers(0, self.N_LABELS, T).astype(np.int64)
                self.samples.append((w, p, l))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


# ---------------------------------------------------------------------------
# Viterbi decode (CRF inference) — lax.scan over time, batched
# ---------------------------------------------------------------------------

def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=False, name=None):
    """Best tag path per sequence. potentials [B, T, N] (emission scores),
    transition_params [N, N]; optional lengths [B] restrict the decode to
    each sequence's valid prefix (positions past the length repeat the
    final valid tag). Returns (scores [B], paths [B, T] int64).
    Dynamic-programming scan — compiler-friendly control flow, no
    python-loop-over-time."""
    if include_bos_eos_tag:
        raise NotImplementedError(
            "include_bos_eos_tag=True (implicit SOS/EOS transitions) is "
            "not supported; add explicit bos/eos rows to the emissions")

    def f(emis, trans, *maybe_len):
        B, T, N = emis.shape
        lens = maybe_len[0] if maybe_len else None

        def step(carry, xs):
            alpha = carry                                   # [B, N]
            e_t, t = xs
            scores = alpha[:, :, None] + trans[None]        # [B, N, N]
            best = scores.max(axis=1) + e_t                 # [B, N]
            back = scores.argmax(axis=1)                    # [B, N]
            if lens is not None:
                active = (t < lens)[:, None]                # [B, 1]
                best = jnp.where(active, best, alpha)       # freeze alpha
                ident = jnp.broadcast_to(
                    jnp.arange(N)[None], (B, N))            # pass-through
                back = jnp.where(active, back, ident)
            return best, back

        alpha0 = emis[:, 0]
        ts = jnp.arange(1, T)
        alpha, backs = jax.lax.scan(
            step, alpha0, (jnp.swapaxes(emis[:, 1:], 0, 1), ts))
        score = alpha.max(axis=1)
        last = alpha.argmax(axis=1)

        def backtrack(carry, back_t):
            tag = carry
            prev = jnp.take_along_axis(back_t, tag[:, None], 1)[:, 0]
            return prev, prev

        _, path_rev = jax.lax.scan(backtrack, last, backs, reverse=True)
        paths = jnp.concatenate([path_rev, last[None]], 0)  # [T, B]
        return score, jnp.swapaxes(paths, 0, 1).astype(jnp.int64)

    args = [potentials, transition_params]
    if lengths is not None:
        args.append(lengths)
    return apply(f, *args, op_name="viterbi_decode")


class ViterbiDecoder:
    """Layer-style wrapper holding transitions (reference ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=False, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


from . import datasets  # noqa: E402  (text/datasets.py submodule)
