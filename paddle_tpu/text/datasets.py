"""paddle.text.datasets — the dataset classes under their reference
import path (python/paddle/text/datasets/__init__.py); implementations
live in the text package root."""
from . import (Conll05st, Imdb, Imikolov, Movielens,  # noqa: F401
               UCIHousing, WMT14, WMT16)

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]

# reference per-dataset submodules (text/datasets/{imdb,wmt16,...}.py):
# all classes live in this one module; the names alias it
import sys as _sys                                         # noqa: E402
conll05 = imdb = imikolov = movielens = uci_housing = wmt14 = wmt16 = \
    _sys.modules[__name__]
