"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle ~2.0 (reference: /root/reference), rebuilt on
JAX/XLA/Pallas/pjit. See SURVEY.md for the blueprint.

Public API mirrors `import paddle`: tensors + ops at top level, `nn`,
`optimizer`, `amp`, `metric`, `io`, `vision`, `jit`, `static`, `distributed`,
and the high-level `Model`.
"""
from .core import dtype as _dtype_mod
from .core.dtype import (bfloat16, bool_, complex64, complex128, float16,
                         float32, float64, get_default_dtype, int8, int16,
                         int32, int64, set_default_dtype, uint8)
from .core.errors import enforce
from .core.flags import get_flags, set_flags
from .core.flags import forward_xla_flags as _forward_xla_flags

# XLA reads XLA_FLAGS once at backend init: forward the comm/compute
# overlap knobs (latency-hiding scheduler, async collectives) before any
# device use. Gated to explicit TPU targets — see core/flags.py.
_forward_xla_flags()
from .core.place import (CPUPlace, CUDAPlace, TPUPlace, TPUPinnedPlace,
                         device_count, get_device, is_compiled_with_cuda,
                         is_compiled_with_tpu, set_device)
from .core.random import get_rng_state, seed, set_rng_state
from .core.tensor import Tensor, enable_grad, no_grad, set_grad_enabled, to_tensor
from .core.autograd import grad

from .ops import *  # noqa: F401,F403  — tensor function library
from .ops import einsum  # noqa: F401

from .framework import Parameter, ParamAttr, save, load  # noqa: F401
from .hapi import Model, summary, flops  # noqa: F401

# submodules reachable as attributes (paddle.nn.Linear, paddle.amp.auto_cast
# ... — matches the reference package layout python/paddle/__init__.py)
from . import amp  # noqa: F401
from . import metric  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import inference  # noqa: F401
from . import text  # noqa: F401
from . import utils  # noqa: F401
from . import quant  # noqa: F401
from . import onnx  # noqa: F401
from . import dataset  # noqa: F401
from . import distribution  # noqa: F401
from . import incubate  # noqa: F401
from . import regularizer  # noqa: F401
from . import profiler  # noqa: F401
from . import observability  # noqa: F401
from .core import monitor  # noqa: F401
from . import device  # noqa: F401

# fluid-era compatibility tail. The reference exposes these through
# paddle.fluid.layers.* (its 2.0 __init__ lists most of them commented
# out); they live at the top level HERE as migration shims so fluid-era
# user code ports with one import change — a deliberate superset of the
# reference's top-level contract.
from .legacy_alias import *  # noqa: F401,F403
from .distributed.parallel import DataParallel  # noqa: F401
from .hapi import callbacks  # noqa: F401
from .static import data  # noqa: F401

# LoD-era type aliases: a LoDTensor is a Tensor plus the host-side length
# descriptor (core/lod.py); VarBase is the eager Tensor
LoDTensor = Tensor
VarBase = Tensor
LoDTensorArray = list
from .core.place import (CUDAPinnedPlace, XPUPlace)  # noqa: F401,E402

# mode switches (reference python/paddle/__init__.py:269-271 maps them
# onto the dygraph toggles: enable_static == disable_dygraph). The
# framework is always-eager with jit/to_static as the graph path, so the
# flag is observable state for ported code, not an execution-engine swap.
from .legacy_alias import (enable_dygraph as disable_static,  # noqa: E402,F401
                           disable_dygraph as enable_static,
                           in_dygraph_mode as in_dynamic_mode)
from . import tensor  # noqa: F401,E402  (paddle.tensor submodule alias)


def batch(reader, batch_size, drop_last=False):
    """Wrap a sample reader into a batched reader (reference
    python/paddle/batch.py:1): `reader` is a zero-arg generator
    function; the result yields lists of `batch_size` samples."""
    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batch_reader

__version__ = "0.3.0"
full_version = __version__
commit = "tpu-native"
