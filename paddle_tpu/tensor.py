"""paddle.tensor — the tensor function library as a submodule.

Reference: python/paddle/tensor/__init__.py:1 groups the tensor ops
(creation/linalg/manipulation/math/random/search/stat...) under one
module that the top level star-imports. Here `ops/` is that library;
this module is the name-parity alias so `import paddle.tensor` /
`paddle.tensor.concat(...)`-style code ports unchanged."""
from . import ops as _ops
from .ops import *            # noqa: F401,F403
from .core.tensor import Tensor, to_tensor  # noqa: F401

__all__ = [n for n in dir(_ops) if not n.startswith("_")] + \
    ["Tensor", "to_tensor"]

# reference paddle/tensor/__init__.py exports these two beyond the op
# library surface
from .legacy_alias import shape, shard_index  # noqa: F401,E402
