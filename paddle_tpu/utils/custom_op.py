"""Custom-op extension API — register user kernels without editing ops/.

Reference: utils/cpp_extension + framework/custom_operator.cc let users
compile C++/CUDA ops against stable headers and register them into the
op registry at import time.

TPU-native: a custom kernel is a JAX-traceable function (jnp composition
or a Pallas TPU kernel — the CUDA analog here); registration wires it
into the eager tape (core.tensor.apply), the AMP lists, and optionally
the paddle namespace / Tensor methods. A custom backward is attached as
jax.custom_vjp, mirroring the reference's (forward, backward) op pairs.

    from paddle_tpu.utils.custom_op import register_op

    @register_op("custom_relu", tensor_method=True)
    def custom_relu(x):
        return jnp.maximum(x, 0)

    # with hand-written backward (e.g. wrapping a Pallas kernel pair):
    register_op("my_gelu", fwd_fn, grad_fn=bwd_fn)
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax

__all__ = ["register_op", "deregister_op", "registered_ops"]

_registry = {}


def register_op(name: str, fn: Optional[Callable] = None, *,
                grad_fn: Optional[Callable] = None,
                tensor_method: bool = False,
                namespace: bool = True,
                amp_list: Optional[str] = None):
    """Register `fn(*raw_arrays, **kwargs) -> array(s)` as op `name`.

    grad_fn(res, cotangent) -> input cotangents, with res = (inputs, out);
    omitted -> autodiff through the traced body (jax.vjp).
    tensor_method -> also attach as Tensor.<name>.
    namespace -> expose as paddle_tpu.<name> / paddle_tpu.ops.<name>.
    amp_list -> "white" (run in bf16 under autocast) or "black" (force f32).
    """
    if fn is None:
        return lambda f: register_op(name, f, grad_fn=grad_fn,
                                     tensor_method=tensor_method,
                                     namespace=namespace, amp_list=amp_list)
    if amp_list not in (None, "white", "black"):
        raise ValueError("amp_list must be 'white' or 'black'")
    from ..core.tensor import Tensor, apply

    def _make_kernel(kwargs):
        """Kwargs are compile-time attrs (reference op Attrs): close over
        them so the custom_vjp callable stays positional-only."""
        if grad_fn is None:
            return lambda *raw: fn(*raw, **kwargs)

        @jax.custom_vjp
        def kernel(*raw):
            return fn(*raw, **kwargs)

        def k_fwd(*raw):
            out = fn(*raw, **kwargs)
            return out, (raw, out)

        def k_bwd(res, g):
            cots = grad_fn(res, g)
            if not isinstance(cots, (tuple, list)):
                cots = (cots,)
            return tuple(cots)

        kernel.defvjp(k_fwd, k_bwd)
        return kernel

    @functools.wraps(fn)
    def op(*args, **kwargs):
        return apply(_make_kernel(kwargs), *args, op_name=name)

    # refuse to shadow core API surface (reference: duplicate op
    # registration is a hard error in OpRegistry)
    import paddle_tpu
    import paddle_tpu.ops as ops_mod
    for mod in ((paddle_tpu, ops_mod) if namespace else ()):
        existing = getattr(mod, name, None)
        if existing is not None and _registry.get(name) is not existing:
            raise ValueError(
                f"register_op: {name!r} already exists on "
                f"{mod.__name__}; pick another name or deregister first")
    if tensor_method and name in Tensor.__dict__ \
            and _registry.get(name) is not Tensor.__dict__[name]:
        raise ValueError(f"register_op: Tensor.{name} already exists")

    _registry[name] = op
    if namespace:
        setattr(ops_mod, name, op)
        setattr(paddle_tpu, name, op)
    if tensor_method:
        setattr(Tensor, name, op)
    if amp_list:
        from .. import amp as amp_mod
        (amp_mod.WHITE_LIST if amp_list == "white"
         else amp_mod.BLACK_LIST).add(name)
    return op


def deregister_op(name: str):
    op = _registry.pop(name, None)
    if op is None:
        return
    import paddle_tpu
    import paddle_tpu.ops as ops_mod
    from ..core.tensor import Tensor
    for mod in (paddle_tpu, ops_mod):
        if getattr(mod, name, None) is op:
            delattr(mod, name)
    if getattr(Tensor, name, None) is op:
        delattr(Tensor, name)
    from .. import amp as amp_mod
    amp_mod.WHITE_LIST.discard(name)
    amp_mod.BLACK_LIST.discard(name)


def registered_ops():
    return dict(_registry)
