"""Per-op microbenchmark harness (op_tester analog —
/root/reference/paddle/fluid/operators/benchmark/op_tester.cc:1).

Tunnel-aware timing: through remote TPU attachments a device->host fetch
costs a large constant RTT, so wall-clocking one call measures the network.
`bench_fn` chains n dependent calls inside each timed window and reports
the MARGINAL time ((t_long - t_short) / (n_long - n_short)), which cancels
the fetch constant; outputs are reduced to scalars on-device.

CLI:  python -m paddle_tpu.utils.op_bench [op ...]   (default: hot set)
"""
from __future__ import annotations

import time
from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["bench_fn", "bench_suite", "HOT_OPS"]


def bench_fn(fn: Callable, *args, n_short=4, n_long=16, repeats=2,
             flops=0) -> Dict[str, float]:
    """fn(*args) -> scalar-reducible pytree. Returns marginal ms/call."""
    def scal(t):
        return sum(jnp.sum(l).astype(jnp.float32)
                   for l in jax.tree_util.tree_leaves(t)) * jnp.float32(1e-12)

    jfn = jax.jit(lambda *a: scal(fn(*a)))
    out = jfn(*args)
    _ = float(out)          # compile + first fetch

    def run(n):
        t0 = time.perf_counter()
        o = None
        for _ in range(n):
            o = jfn(*args)
        _ = float(o)
        return time.perf_counter() - t0

    best = float("inf")
    for _ in range(repeats):
        d1, d2 = run(n_short), run(n_long)
        delta = (d2 - d1) / (n_long - n_short)
        if delta > 0:
            best = min(best, delta)
    if best == float("inf"):
        best = run(n_long) / n_long
    res = {"ms": best * 1e3}
    if flops:
        res["tflops"] = flops / best / 1e12
    return res


def _mk(shape, dtype=jnp.bfloat16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=shape) * 0.1, dtype)


def _adam_update(p, g, m, v):
    m2 = 0.9 * m + 0.1 * g
    v2 = 0.999 * v + 0.001 * g * g
    return p - 1e-3 * m2 / (jnp.sqrt(v2) + 1e-8), m2, v2


def HOT_OPS():
    """BASELINE.json north-star op set: matmul, conv, layer_norm, softmax,
    fused attention, adam."""
    from ..ops.pallas.flash_attention import flash_attention
    B, T, H, D = 8, 1024, 12, 64
    x = _mk((8192, 768))
    w = _mk((768, 3072))
    img = _mk((32, 224, 224, 3), jnp.bfloat16)
    kern = _mk((7, 7, 3, 64))
    h = _mk((8192, 768), jnp.float32)
    q = _mk((B, T, H, D))
    p32 = _mk((8192, 768), jnp.float32)
    return {
        "matmul_8192x768x3072": (lambda: (
            lambda a, b: a @ b, (x, w),
            {"flops": 2 * 8192 * 768 * 3072})),
        "conv2d_7x7_s2": (lambda: (
            lambda i, k: jax.lax.conv_general_dilated(
                i, k, (2, 2), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC")), (img, kern),
            {"flops": 2 * 32 * 112 * 112 * 64 * 7 * 7 * 3})),
        "layer_norm_8192x768": (lambda: (
            lambda a: (a - a.mean(-1, keepdims=True))
            / jnp.sqrt(a.var(-1, keepdims=True) + 1e-5), (h,), {})),
        "softmax_8192x768": (lambda: (
            lambda a: jax.nn.softmax(a, axis=-1), (h,), {})),
        "flash_attention_8x1024x12x64": (lambda: (
            lambda a: flash_attention(a, a, a, causal=True), (q,),
            {"flops": 4 * B * H * T * T * D})),
        "adam_update_8192x768": (lambda: (
            _adam_update, (p32, p32, p32, p32), {})),
    }


def eager_overhead(n_short=60, n_long=240, repeats=3):
    """µs/op of the EAGER dispatch path — Tensor.apply + tape recording
    (VERDICT r4 Next #10; the reference tracked the same quantity with
    operators/benchmark/op_tester.cc). Chains n dependent ops on [8, 8]
    tensors (device compute is negligible at that size) with ONE host
    sync per window; the marginal time is the per-op python-side cost.
    Returns {op: µs/op}."""
    from ..core.tensor import to_tensor
    from ..nn import functional as F

    eye = to_tensor(np.eye(8, dtype=np.float32))
    one = to_tensor(np.ones((8, 8), np.float32))

    def chain_add(x, n):
        for _ in range(n):
            x = x + one
        return x

    def chain_matmul(x, n):
        for _ in range(n):
            x = x.matmul(eye)          # identity keeps values bounded
        return x

    def chain_layer_norm(x, n):
        for _ in range(n):
            x = F.layer_norm(x, [8])
        return x

    out = {}
    for name, chain in (("add", chain_add), ("matmul", chain_matmul),
                        ("layer_norm", chain_layer_norm)):
        def run(n):
            x = to_tensor(np.ones((8, 8), np.float32))
            t0 = time.perf_counter()
            y = chain(x, n)
            float(np.asarray(y.numpy()).sum())
            return time.perf_counter() - t0

        run(4)                          # warm the per-op jit caches
        best = float("inf")
        for _ in range(repeats):
            d1, d2 = run(n_short), run(n_long)
            delta = (d2 - d1) / (n_long - n_short)
            if delta > 0:
                best = min(best, delta)
        if best == float("inf"):
            best = run(n_long) / n_long
        out[name] = best * 1e6
    return out


def bench_suite(names=None):
    ops = HOT_OPS()
    names = names or list(ops)
    rows = []
    for name in names:
        fn, args, extra = ops[name]()
        r = bench_fn(fn, *args, **extra)
        rows.append((name, r))
        tfl = f"  {r['tflops']:7.1f} TF/s" if "tflops" in r else ""
        print(f"{name:36s} {r['ms']:9.3f} ms{tfl}")
    return rows


if __name__ == "__main__":
    import sys
    if "--eager" in sys.argv:
        for op, us in eager_overhead().items():
            print(f"eager {op:12s} {us:8.1f} us/op")
    else:
        bench_suite(sys.argv[1:] or None)
