"""Per-op microbenchmark harness (op_tester analog —
/root/reference/paddle/fluid/operators/benchmark/op_tester.cc:1).

Tunnel-aware timing: through remote TPU attachments a device->host fetch
costs a large constant RTT, so wall-clocking one call measures the network.
`bench_fn` chains n dependent calls inside each timed window and reports
the MARGINAL time ((t_long - t_short) / (n_long - n_short)), which cancels
the fetch constant; outputs are reduced to scalars on-device.

CLI:  python -m paddle_tpu.utils.op_bench [op ...]   (default: hot set)
"""
from __future__ import annotations

import time
from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["bench_fn", "bench_suite", "HOT_OPS"]


def bench_fn(fn: Callable, *args, n_short=4, n_long=16, repeats=2,
             flops=0) -> Dict[str, float]:
    """fn(*args) -> scalar-reducible pytree. Returns marginal ms/call."""
    def scal(t):
        return sum(jnp.sum(l).astype(jnp.float32)
                   for l in jax.tree_util.tree_leaves(t)) * jnp.float32(1e-12)

    jfn = jax.jit(lambda *a: scal(fn(*a)))
    out = jfn(*args)
    _ = float(out)          # compile + first fetch

    def run(n):
        t0 = time.perf_counter()
        o = None
        for _ in range(n):
            o = jfn(*args)
        _ = float(o)
        return time.perf_counter() - t0

    best = float("inf")
    for _ in range(repeats):
        d1, d2 = run(n_short), run(n_long)
        delta = (d2 - d1) / (n_long - n_short)
        if delta > 0:
            best = min(best, delta)
    if best == float("inf"):
        best = run(n_long) / n_long
    res = {"ms": best * 1e3}
    if flops:
        res["tflops"] = flops / best / 1e12
    return res


def _mk(shape, dtype=jnp.bfloat16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=shape) * 0.1, dtype)


def _adam_update(p, g, m, v):
    m2 = 0.9 * m + 0.1 * g
    v2 = 0.999 * v + 0.001 * g * g
    return p - 1e-3 * m2 / (jnp.sqrt(v2) + 1e-8), m2, v2


def HOT_OPS():
    """BASELINE.json north-star op set: matmul, conv, layer_norm, softmax,
    fused attention, adam."""
    from ..ops.pallas.flash_attention import flash_attention
    B, T, H, D = 8, 1024, 12, 64
    x = _mk((8192, 768))
    w = _mk((768, 3072))
    img = _mk((32, 224, 224, 3), jnp.bfloat16)
    kern = _mk((7, 7, 3, 64))
    h = _mk((8192, 768), jnp.float32)
    q = _mk((B, T, H, D))
    p32 = _mk((8192, 768), jnp.float32)
    return {
        "matmul_8192x768x3072": (lambda: (
            lambda a, b: a @ b, (x, w),
            {"flops": 2 * 8192 * 768 * 3072})),
        "conv2d_7x7_s2": (lambda: (
            lambda i, k: jax.lax.conv_general_dilated(
                i, k, (2, 2), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC")), (img, kern),
            {"flops": 2 * 32 * 112 * 112 * 64 * 7 * 7 * 3})),
        "layer_norm_8192x768": (lambda: (
            lambda a: (a - a.mean(-1, keepdims=True))
            / jnp.sqrt(a.var(-1, keepdims=True) + 1e-5), (h,), {})),
        "softmax_8192x768": (lambda: (
            lambda a: jax.nn.softmax(a, axis=-1), (h,), {})),
        "flash_attention_8x1024x12x64": (lambda: (
            lambda a: flash_attention(a, a, a, causal=True), (q,),
            {"flops": 4 * B * H * T * T * D})),
        "adam_update_8192x768": (lambda: (
            _adam_update, (p32, p32, p32, p32), {})),
    }


def bench_suite(names=None):
    ops = HOT_OPS()
    names = names or list(ops)
    rows = []
    for name in names:
        fn, args, extra = ops[name]()
        r = bench_fn(fn, *args, **extra)
        rows.append((name, r))
        tfl = f"  {r['tflops']:7.1f} TF/s" if "tflops" in r else ""
        print(f"{name:36s} {r['ms']:9.3f} ms{tfl}")
    return rows


if __name__ == "__main__":
    import sys
    bench_suite(sys.argv[1:] or None)
