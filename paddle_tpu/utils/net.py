"""Shared socket helpers for the native-service clients (PS, TCPStore,
inference serve) — one place for the recv-until-n loop."""
from __future__ import annotations

__all__ = ["recv_exact"]


def recv_exact(sock, n: int, what: str = "peer") -> bytes:
    """Read exactly n bytes or raise ConnectionError on EOF."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError(f"{what} closed connection")
        buf.extend(chunk)
    return bytes(buf)
