"""paddle.utils tail (reference python/paddle/utils/__init__.py):
deprecated, try_import, require_version, unique_name, download facade,
legacy profiler aliases."""
from __future__ import annotations

import functools
import importlib
import warnings

__all__ = ["deprecated", "try_import", "require_version", "unique_name",
           "download", "Profiler", "ProfilerOptions", "get_profiler",
           "OpLastCheckpointChecker"]


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator emitting a DeprecationWarning on call (reference
    utils/deprecated.py)."""
    def wrap(fn):
        msg = f"API {fn.__name__} is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f"; use {update_to} instead"
        if reason:
            msg += f" ({reason})"

        @functools.wraps(fn)
        def inner(*a, **kw):
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*a, **kw)
        return inner
    return wrap


def try_import(module_name, err_msg=None):
    """Import or raise a friendly ImportError (reference
    utils/lazy_import.py try_import)."""
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"Failed importing {module_name}. This likely "
            f"means the optional dependency is not installed.")


def require_version(min_version, max_version=None):
    """Check the installed framework version against a range (reference
    utils/install_check-style require_version)."""
    from .. import __version__

    def parse(v):
        return tuple(int(x) for x in str(v).split(".")[:3])
    cur = parse(__version__)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > allowed {max_version}")
    return True


class _UniqueNameModule:
    """paddle.utils.unique_name (reference fluid/unique_name.py):
    generate(prefix) -> prefix_N, guard() scopes the counters, switch()
    swaps generators."""

    def __init__(self):
        self._counters = {}

    def generate(self, key):
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        return f"{key}_{n}"

    def switch(self, new_generator=None):
        old = dict(self._counters)
        self._counters = {} if new_generator is None else new_generator
        return old

    def guard(self, new_generator=None):
        import contextlib

        @contextlib.contextmanager
        def g():
            old = self._counters
            self._counters = {}
            try:
                yield
            finally:
                self._counters = old
        return g()


unique_name = _UniqueNameModule()


def download(url, path=None, md5sum=None, **kw):
    """Zero-egress environment: downloads are unavailable by design;
    datasets read local files (see paddle.vision.datasets docstrings)."""
    raise RuntimeError(
        "paddle.utils.download: this environment has no network egress; "
        "place the file locally and pass its path to the dataset/loader")


# legacy fluid profiler aliases over paddle_tpu.profiler
class ProfilerOptions:
    def __init__(self, options=None):
        self.options = options or {}


def Profiler(*a, **kw):
    from .. import profiler as prof
    return prof.Profiler(*a, **kw) if hasattr(prof, "Profiler") else prof


def get_profiler(*a, **kw):
    from .. import profiler as prof
    return prof


class OpLastCheckpointChecker:
    """Compat checker for op-version checkpoints (reference
    utils/op_version.py); custom ops here version through
    utils.custom_op's registry, so every query reports 'current'."""

    def check(self, op_name, **kw):
        return True
