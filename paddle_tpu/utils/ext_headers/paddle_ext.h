/* paddle_tpu stable custom-op ABI (single header, C linkage).
 *
 * Reference: the stable-header custom-op surface in
 * /root/reference/paddle/fluid/extension/include/ext_*.h consumed by
 * python/paddle/utils/cpp_extension. The reference ships a C++ Tensor
 * class; here the ABI is plain C structs so any compiler (and ctypes)
 * can bind without name mangling or libstdc++ layout coupling.
 *
 * An op "name" exports:
 *   void name__fwd(const pd_tensor* ins, int n_in,
 *                  pd_tensor* outs, int n_out);
 * and optionally the gradient kernel:
 *   void name__bwd(const pd_tensor* ins, int n_in,
 *                  const pd_tensor* grads, int n_grad,
 *                  pd_tensor* dins, int n_dins);
 *
 * Output buffers are allocated by the framework before the call (shapes
 * from the op's out_shapes rule on the Python side); kernels only fill
 * .data. pd_numel is a convenience for elementwise loops.
 */
#ifndef PADDLE_TPU_EXT_H_
#define PADDLE_TPU_EXT_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

enum pd_dtype {
  PD_FLOAT32 = 0,
  PD_FLOAT64 = 1,
  PD_INT32 = 2,
  PD_INT64 = 3,
  PD_UINT8 = 4,
  PD_BOOL = 5,
};

typedef struct {
  void* data;
  const int64_t* shape;
  int32_t ndim;
  int32_t dtype; /* pd_dtype */
} pd_tensor;

static inline int64_t pd_numel(const pd_tensor* t) {
  int64_t n = 1;
  for (int32_t i = 0; i < t->ndim; ++i) n *= t->shape[i];
  return n;
}

/* Kernel definitions live outside this header's extern "C" block, so the
 * macro itself must carry the C linkage. */
#ifdef __cplusplus
#define PD_KERNEL(name) extern "C" void name
#else
#define PD_KERNEL(name) void name
#endif

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* PADDLE_TPU_EXT_H_ */
