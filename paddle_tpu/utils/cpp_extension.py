"""JIT-compile user C++ custom ops and register them as paddle ops.

Reference: python/paddle/utils/cpp_extension (load/setup compiling
custom_relu_op.cc against stable ext headers, registered through
framework/custom_operator.cc into the op registry; tests
tests/custom_op/test_custom_attrs_jit.py — SURVEY.md §2 row 53, §4.8).

TPU-native split of the capability:
  * TPU-device custom kernels -> `utils.custom_op.register_op` with a
    Pallas body (that is the CUDA-kernel analog; nothing to compile here).
  * Host/CPU custom ops (IO, tokenizers, CPU reference kernels) -> THIS
    module: g++ -shared against the stable C ABI in
    ext_headers/paddle_ext.h, bound via ctypes, lifted into the op system
    with `jax.pure_callback` so the op works under BOTH the eager tape and
    jit (the callback runs host-side; XLA treats it as an opaque call).

    mod = cpp_extension.load(name="my_ops", sources=["relu.cc"])
    y = mod.custom_relu(x)          # eager Tensor or inside jit

A `name__bwd` symbol, when exported, becomes the op's custom VJP —
mirroring the reference's paired forward/backward custom kernels.
"""
from __future__ import annotations

import ctypes
import functools
import os
import subprocess
import tempfile
from typing import Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["load", "get_include", "CppExtensionModule"]

_HDR_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "ext_headers")

_DTYPE_CODES = {
    np.dtype(np.float32): 0, np.dtype(np.float64): 1,
    np.dtype(np.int32): 2, np.dtype(np.int64): 3,
    np.dtype(np.uint8): 4, np.dtype(np.bool_): 5,
}


def get_include() -> str:
    """Directory holding paddle_ext.h (reference: paddle.sysconfig style)."""
    return _HDR_DIR


class _PdTensor(ctypes.Structure):
    _fields_ = [("data", ctypes.c_void_p),
                ("shape", ctypes.POINTER(ctypes.c_int64)),
                ("ndim", ctypes.c_int32),
                ("dtype", ctypes.c_int32)]


def _as_pd(arr: np.ndarray, shapes_keepalive: list) -> _PdTensor:
    shp = (ctypes.c_int64 * max(arr.ndim, 1))(*(arr.shape or (0,)))
    shapes_keepalive.append(shp)
    return _PdTensor(
        data=arr.ctypes.data_as(ctypes.c_void_p),
        shape=ctypes.cast(shp, ctypes.POINTER(ctypes.c_int64)),
        ndim=arr.ndim,
        dtype=_DTYPE_CODES[arr.dtype])


def _compile(name: str, sources: Sequence[str], extra_cflags=()) -> str:
    build_dir = os.path.join(tempfile.gettempdir(), "paddle_tpu_ext", name)
    os.makedirs(build_dir, exist_ok=True)
    out = os.path.join(build_dir, f"{name}.so")
    srcs = [os.path.abspath(s) for s in sources]
    stamp = max((os.path.getmtime(s) for s in srcs), default=0.0)
    if not os.path.exists(out) or os.path.getmtime(out) < stamp:
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
               f"-I{_HDR_DIR}", *extra_cflags, "-o", out, *srcs]
        res = subprocess.run(cmd, capture_output=True, text=True)
        if res.returncode != 0:
            raise RuntimeError(
                f"cpp_extension build of {name!r} failed:\n{res.stderr}")
    return out


def _exported_ops(so_path: str) -> Dict[str, bool]:
    """{op_name: has_bwd} from the .so's dynamic symbol table (nm -D)."""
    res = subprocess.run(["nm", "-D", "--defined-only", so_path],
                         capture_output=True, text=True, check=True)
    syms = {line.split()[-1] for line in res.stdout.splitlines() if line}
    ops = {}
    for s in syms:
        if s.endswith("__fwd"):
            base = s[:-len("__fwd")]
            ops[base] = f"{base}__bwd" in syms
    return ops


def _call_kernel(cfun, ins: Sequence[np.ndarray],
                 out_specs) -> tuple:
    keep = []
    in_arr = (_PdTensor * max(len(ins), 1))(
        *[_as_pd(np.ascontiguousarray(a), keep) for a in ins])
    outs = [np.zeros(shape, dtype) for shape, dtype in out_specs]
    out_arr = (_PdTensor * max(len(outs), 1))(
        *[_as_pd(o, keep) for o in outs])
    cfun(in_arr, len(ins), out_arr, len(outs))
    return tuple(outs)


class CppExtensionModule:
    """Namespace holding the ops exported by one compiled extension."""

    def __init__(self, name, so_path, ops):
        self.name = name
        self.so_path = so_path
        self._ops = ops

    def __getattr__(self, item):
        raise AttributeError(
            f"extension {self.name!r} exports {sorted(self._ops)}; "
            f"no op {item!r}")

    def __repr__(self):
        return f"<CppExtensionModule {self.name} ops={sorted(self._ops)}>"


def load(name: str, sources: Sequence[str],
         out_shapes: Optional[Dict[str, Callable]] = None,
         num_outputs: Optional[Dict[str, int]] = None,
         extra_cflags: Sequence[str] = (),
         register: bool = False, verbose: bool = False):
    """Compile `sources` and return a module exposing each `op__fwd` as a
    paddle-callable op (usable on eager Tensors and inside jit).

    out_shapes[op]: fn(*jax.ShapeDtypeStruct) -> list[(shape, dtype)] for
    ops whose outputs are not same-shape-as-input-0 (the default rule, as
    in the reference's InferShape fallback). num_outputs[op] defaults 1.
    register=True additionally installs each op into the paddle namespace
    via utils.custom_op.register_op.
    """
    so_path = _compile(name, sources, extra_cflags)
    lib = ctypes.CDLL(so_path)
    ops = _exported_ops(so_path)
    if verbose:
        print(f"cpp_extension {name}: {so_path} ops={sorted(ops)}")
    if not ops:
        raise RuntimeError(
            f"{name}: no `<op>__fwd` symbols exported — declare kernels "
            f'as extern "C" (see {_HDR_DIR}/paddle_ext.h)')

    mod = CppExtensionModule(name, so_path, ops)
    from ..core.tensor import apply

    for op_name, has_bwd in ops.items():
        fwd_c = getattr(lib, f"{op_name}__fwd")
        fwd_c.restype = None
        bwd_c = getattr(lib, f"{op_name}__bwd") if has_bwd else None
        if bwd_c is not None:
            bwd_c.restype = None
        n_out = (num_outputs or {}).get(op_name, 1)
        shape_fn = (out_shapes or {}).get(op_name)

        def make(op_name=op_name, fwd_c=fwd_c, bwd_c=bwd_c, n_out=n_out,
                 shape_fn=shape_fn):
            def out_specs_of(avals):
                if shape_fn is not None:
                    return [(tuple(s), np.dtype(d))
                            for s, d in shape_fn(*avals)]
                a0 = avals[0]
                return [(tuple(a0.shape), np.dtype(a0.dtype))] * n_out

            def host_fwd(*arrs):
                specs = out_specs_of([jax.ShapeDtypeStruct(a.shape, a.dtype)
                                      for a in arrs])
                return _call_kernel(fwd_c, arrs, specs)

            def fwd_raw(*raw):
                specs = out_specs_of(raw)
                result = jax.pure_callback(
                    host_fwd,
                    tuple(jax.ShapeDtypeStruct(s, d) for s, d in specs),
                    *raw, vmap_method="sequential")
                return result[0] if len(result) == 1 else result

            if bwd_c is None:
                kernel = fwd_raw
            else:
                @jax.custom_vjp
                def kernel(*raw):
                    return fwd_raw(*raw)

                def k_fwd(*raw):
                    return fwd_raw(*raw), raw

                def k_bwd(raw, g):
                    gs = g if isinstance(g, (tuple, list)) else (g,)

                    def host_bwd(*flat):
                        ins = flat[:len(raw)]
                        grads = flat[len(raw):]
                        keep = []
                        in_arr = (_PdTensor * max(len(ins), 1))(
                            *[_as_pd(np.ascontiguousarray(a), keep)
                              for a in ins])
                        g_arr = (_PdTensor * max(len(grads), 1))(
                            *[_as_pd(np.ascontiguousarray(a), keep)
                              for a in grads])
                        # the kernel still receives a dins slot per input
                        # (ABI stability); integer slots are discarded
                        douts = [np.zeros(a.shape,
                                          a.dtype if np.issubdtype(
                                              a.dtype, np.inexact)
                                          else np.float32) for a in ins]
                        d_arr = (_PdTensor * max(len(douts), 1))(
                            *[_as_pd(o, keep) for o in douts])
                        bwd_c(in_arr, len(ins), g_arr, len(grads),
                              d_arr, len(douts))
                        return tuple(o for o, a in zip(douts, ins)
                                     if np.issubdtype(a.dtype, np.inexact))

                    inexact = [np.issubdtype(np.dtype(r.dtype), np.inexact)
                               for r in raw]
                    dflt = jax.pure_callback(
                        host_bwd,
                        tuple(jax.ShapeDtypeStruct(r.shape, r.dtype)
                              for r, ix in zip(raw, inexact) if ix),
                        *raw, *gs, vmap_method="sequential")
                    dflt = iter(dflt)
                    # custom_vjp cotangent rule: float0 zeros for integer
                    # primals, real cotangents for inexact ones
                    return tuple(
                        next(dflt) if ix else
                        np.zeros(r.shape, jax.dtypes.float0)
                        for r, ix in zip(raw, inexact))

                kernel.defvjp(k_fwd, k_bwd)

            @functools.wraps(kernel)
            def op(*args, **kwargs):
                if kwargs:
                    # the C ABI carries tensors only; silently dropping
                    # attrs would be silently-wrong numerics
                    raise TypeError(
                        f"{op_name}() got unexpected keyword arguments "
                        f"{sorted(kwargs)}: cpp_extension ops take tensor "
                        f"positional args only (bake attrs into the C++ "
                        f"source, or use utils.custom_op.register_op for "
                        f"attr-carrying custom ops)")
                return apply(kernel, *args, op_name=op_name)
            op.__name__ = op_name
            return op

        bound = make()
        setattr(mod, op_name, bound)
        if register:
            from .custom_op import register_op
            register_op(op_name, bound.__wrapped__
                        if hasattr(bound, "__wrapped__") else bound)
    return mod
