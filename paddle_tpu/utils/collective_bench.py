"""ICI/DCN collective bandwidth probe (BASELINE.md: the Fleet allreduce-BW
analog; reference tooling lived in benchmark scripts over
operators/collective/).

Sweeps buffer sizes through psum/all_gather/reduce_scatter under
shard_map over the full device mesh and reports algorithmic bus bandwidth
busBW = 2*(n-1)/n * bytes / t for allreduce (NCCL-tests convention; the
same formula the reference's fleet benchmarks quote), so numbers compare
directly against NCCL baselines. On a single chip this measures loopback
(no ICI); its purpose is the multi-chip pod where XLA emits ICI ring
collectives.

CLI: python -m paddle_tpu.utils.collective_bench [--sizes MB,MB,...]
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["bench_collectives"]


def _time_op(fn, x, n_short=2, n_long=8):
    jax.block_until_ready(fn(x))

    def run(n):
        t0 = time.perf_counter()
        o = None
        for _ in range(n):
            o = fn(x)
        jax.block_until_ready(o)
        return time.perf_counter() - t0

    d1, d2 = run(n_short), run(n_long)
    delta = (d2 - d1) / (n_long - n_short)
    return delta if delta > 0 else run(n_long) / n_long


def bench_collectives(sizes_mb=(1, 4, 16, 64), devices=None):
    """`size` follows the NCCL-tests convention: per-rank buffer bytes.
    Input is [n, per_rank] with row i on device i (distinct buffers)."""
    devices = devices or jax.devices()
    n = len(devices)
    mesh = Mesh(np.array(devices), ("x",))
    rows = []
    for mb in sizes_mb:
        per = max(int(mb * 1e6 / 4), n)
        per = ((per + n - 1) // n) * n   # psum_scatter needs per % n == 0
        size_bytes = per * 4
        x = jnp.broadcast_to(
            jnp.arange(n, dtype=jnp.float32)[:, None], (n, per))

        ar = jax.jit(jax.shard_map(
            lambda a: jax.lax.psum(a, "x"), mesh=mesh,
            in_specs=P("x", None), out_specs=P(None, None),
            check_vma=False))
        bus_ar = 2 * (n - 1) / n * size_bytes / _time_op(ar, x) / 1e9

        ag = jax.jit(jax.shard_map(
            lambda a: jax.lax.all_gather(a, "x", axis=0, tiled=True),
            mesh=mesh, in_specs=P("x", None), out_specs=P(None, None),
            check_vma=False))
        bus_ag = (n - 1) / n * size_bytes / _time_op(ag, x) / 1e9

        rs = jax.jit(jax.shard_map(
            lambda a: jax.lax.psum_scatter(a, "x", scatter_dimension=1,
                                           tiled=True),
            mesh=mesh, in_specs=P("x", None), out_specs=P("x", None),
            check_vma=False))
        bus_rs = (n - 1) / n * size_bytes / _time_op(rs, x) / 1e9

        rows.append({"MB": mb, "allreduce_GBps": bus_ar,
                     "allgather_GBps": bus_ag, "reducescatter_GBps": bus_rs})
        print(f"{mb:6.1f} MB  allreduce {bus_ar:8.2f} GB/s  "
              f"allgather {bus_ag:8.2f} GB/s  "
              f"reduce_scatter {bus_rs:8.2f} GB/s   (n={n})")
    return rows


if __name__ == "__main__":
    import sys
    sizes = (1, 4, 16, 64)
    for a in sys.argv[1:]:
        if a.startswith("--sizes"):
            sizes = tuple(float(s) for s in a.split("=")[1].split(","))
    bench_collectives(sizes)
