"""Bounded retry/backoff + wall-clock watchdog primitives.

The fault-tolerance layer (docs/fault_tolerance.md) routes every
transient-failure-prone call through here: `TCPStore._req` reconnects,
`RemoteFS` verbs, and `elastic.run_with_recovery` restarts all use the
same bounded exponential backoff with jitter, and hang-prone control
calls (`Store.barrier`) run under `call_with_watchdog` so a wedged peer
raises a typed TimeoutError instead of blocking forever.

No reference analog: the reference stack aborts on the first failure
(launch_utils.py watch_local_trainers); this module is what turns those
aborts into bounded retries.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Iterable, Tuple, Type

__all__ = ["DeadlineExceeded", "WatchdogTimeout", "backoff_delays",
           "retry_call", "retry", "call_with_watchdog"]


class DeadlineExceeded(TimeoutError):
    """A retry loop ran out of wall-clock budget before succeeding."""


class WatchdogTimeout(TimeoutError):
    """A watchdogged call did not return within its wall-clock bound."""


def backoff_delays(retries: int, base_delay: float = 0.05,
                   max_delay: float = 2.0, jitter: float = 0.5,
                   rng: random.Random = None):
    """Yield `retries` sleep durations: capped exponential backoff with
    multiplicative jitter in [1, 1+jitter) (decorrelates gang restarts)."""
    rng = rng or random
    for i in range(retries):
        d = min(max_delay, base_delay * (2.0 ** i))
        yield d * (1.0 + jitter * rng.random())


def retry_call(fn: Callable, *args,
               retries: int = 3,
               base_delay: float = 0.05,
               max_delay: float = 2.0,
               jitter: float = 0.5,
               retry_on: Tuple[Type[BaseException], ...] = (
                   ConnectionError, TimeoutError, OSError),
               deadline: float = None,
               on_retry: Callable = None,
               sleep: Callable[[float], None] = time.sleep,
               **kwargs):
    """Call `fn(*args, **kwargs)`, retrying on exceptions in the
    `retry_on` allowlist — at most `retries` retries (retries+1 attempts
    total), bounded exponential backoff with jitter between attempts.

    `deadline` is an optional wall-clock budget in seconds for the WHOLE
    loop: when sleeping for the next attempt would cross it, the loop
    raises `DeadlineExceeded` chained to the last failure instead of
    sleeping. `on_retry(attempt, exc, delay)` observes each retry.
    Non-allowlisted exceptions propagate immediately.
    """
    t0 = time.monotonic()
    delays = backoff_delays(retries, base_delay, max_delay, jitter)
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            attempt += 1
            if attempt > retries:
                raise
            delay = next(delays)
            if deadline is not None and \
                    time.monotonic() - t0 + delay > deadline:
                raise DeadlineExceeded(
                    f"retry of {getattr(fn, '__name__', fn)!r} exceeded "
                    f"{deadline}s deadline after {attempt} attempts"
                ) from e
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)


def retry(**policy):
    """Decorator form of `retry_call`: `@retry(retries=5, retry_on=(...))`."""

    def deco(fn):
        def wrapped(*args, **kwargs):
            return retry_call(fn, *args, **policy, **kwargs)
        wrapped.__name__ = getattr(fn, "__name__", "retried")
        wrapped.__doc__ = fn.__doc__
        wrapped.__wrapped__ = fn
        return wrapped
    return deco


def call_with_watchdog(fn: Callable, timeout: float, what: str = "call",
                       *args, **kwargs):
    """Run `fn(*args, **kwargs)` under a wall-clock watchdog: if it has
    not returned after `timeout` seconds, raise `WatchdogTimeout`.

    The call runs in a daemon worker thread; on timeout the worker is
    abandoned (Python threads cannot be killed), which is exactly the
    right trade for hung control-plane RPCs — the caller gets a typed,
    catchable error instead of blocking forever, and the leaked thread
    dies with the process. `timeout=None` degrades to a plain call.
    """
    if timeout is None:
        return fn(*args, **kwargs)
    result = {}
    done = threading.Event()

    def _run():
        try:
            result["value"] = fn(*args, **kwargs)
        except BaseException as e:          # surfaced in the caller
            result["exc"] = e
        finally:
            done.set()

    t = threading.Thread(target=_run, daemon=True,
                         name=f"watchdog:{what}")
    t.start()
    if not done.wait(timeout):
        raise WatchdogTimeout(f"{what} did not finish within {timeout}s")
    if "exc" in result:
        raise result["exc"]
    return result.get("value")
