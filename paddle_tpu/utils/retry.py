"""Bounded retry/backoff + wall-clock watchdog primitives.

The fault-tolerance layer (docs/fault_tolerance.md) routes every
transient-failure-prone call through here: `TCPStore._req` reconnects,
`RemoteFS` verbs, and `elastic.run_with_recovery` restarts all use the
same bounded exponential backoff with jitter, and hang-prone control
calls (`Store.barrier`) run under `call_with_watchdog` so a wedged peer
raises a typed TimeoutError instead of blocking forever.

No reference analog: the reference stack aborts on the first failure
(launch_utils.py watch_local_trainers); this module is what turns those
aborts into bounded retries.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Iterable, Tuple, Type

__all__ = ["DeadlineExceeded", "WatchdogTimeout", "backoff_delays",
           "retry_call", "retry", "call_with_watchdog",
           "RetryBudget", "CircuitBreaker"]


class DeadlineExceeded(TimeoutError):
    """A retry loop ran out of wall-clock budget before succeeding."""


class WatchdogTimeout(TimeoutError):
    """A watchdogged call did not return within its wall-clock bound."""


def backoff_delays(retries: int, base_delay: float = 0.05,
                   max_delay: float = 2.0, jitter: float = 0.5,
                   rng: random.Random = None):
    """Yield `retries` sleep durations: capped exponential backoff with
    multiplicative jitter in [1, 1+jitter) (decorrelates gang restarts)."""
    rng = rng or random
    for i in range(retries):
        d = min(max_delay, base_delay * (2.0 ** i))
        yield d * (1.0 + jitter * rng.random())


def retry_call(fn: Callable, *args,
               retries: int = 3,
               base_delay: float = 0.05,
               max_delay: float = 2.0,
               jitter: float = 0.5,
               retry_on: Tuple[Type[BaseException], ...] = (
                   ConnectionError, TimeoutError, OSError),
               deadline: float = None,
               on_retry: Callable = None,
               sleep: Callable[[float], None] = time.sleep,
               **kwargs):
    """Call `fn(*args, **kwargs)`, retrying on exceptions in the
    `retry_on` allowlist — at most `retries` retries (retries+1 attempts
    total), bounded exponential backoff with jitter between attempts.

    `deadline` is an optional wall-clock budget in seconds for the WHOLE
    loop: when sleeping for the next attempt would cross it, the loop
    raises `DeadlineExceeded` chained to the last failure instead of
    sleeping. `on_retry(attempt, exc, delay)` observes each retry.
    Non-allowlisted exceptions propagate immediately.
    """
    t0 = time.monotonic()
    delays = backoff_delays(retries, base_delay, max_delay, jitter)
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            attempt += 1
            if attempt > retries:
                raise
            delay = next(delays)
            if deadline is not None and \
                    time.monotonic() - t0 + delay > deadline:
                raise DeadlineExceeded(
                    f"retry of {getattr(fn, '__name__', fn)!r} exceeded "
                    f"{deadline}s deadline after {attempt} attempts"
                ) from e
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)


def retry(**policy):
    """Decorator form of `retry_call`: `@retry(retries=5, retry_on=(...))`."""

    def deco(fn):
        def wrapped(*args, **kwargs):
            return retry_call(fn, *args, **policy, **kwargs)
        wrapped.__name__ = getattr(fn, "__name__", "retried")
        wrapped.__doc__ = fn.__doc__
        wrapped.__wrapped__ = fn
        return wrapped
    return deco


class RetryBudget:
    """Token-bucket retry budget: retries are a bounded FRACTION of real
    traffic, not a per-request multiplier.

    Per-request retry caps compose badly under fleet-wide failure — with
    every backend down, N clients x R retries is an R-fold traffic
    amplification aimed at whatever comes back up first. A budget makes
    retries proportional: every primary attempt deposits ``ratio``
    tokens (capped at ``cap``), every retry spends one, and when the
    bucket is empty `try_spend` refuses — the caller fails fast with a
    typed error instead of hammering. ``min_tokens`` seeds the bucket so
    the first failures of a quiet process can still fail over.

    Thread-safe; the serving router shares one budget across all
    connection threads.
    """

    def __init__(self, ratio: float = 0.2, cap: float = 32.0,
                 min_tokens: float = 4.0):
        if ratio < 0:
            raise ValueError("ratio must be >= 0")
        self.ratio = float(ratio)
        self.cap = float(cap)
        self._tokens = min(float(min_tokens), self.cap)
        self._lock = threading.Lock()
        self.spent = 0           # granted retries
        self.denied = 0          # refused retries (budget empty)

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def record_request(self, n: int = 1):
        """Deposit for `n` primary attempts."""
        with self._lock:
            self._tokens = min(self.cap, self._tokens + self.ratio * n)

    def try_spend(self, cost: float = 1.0) -> bool:
        """Take one retry from the budget; False when exhausted."""
        with self._lock:
            if self._tokens >= cost:
                self._tokens -= cost
                self.spent += 1
                return True
            self.denied += 1
            return False


class CircuitBreaker:
    """Per-dependency circuit breaker: closed -> open -> half-open.

    `record_failure` trips the breaker OPEN after ``failure_threshold``
    CONSECUTIVE failures; while open, `allow()` refuses instantly (the
    caller skips the dependency without paying a connect timeout). After
    ``reset_timeout`` seconds the breaker lets ONE probe through
    (HALF_OPEN); the probe's `record_success` closes the breaker, its
    `record_failure` re-opens it for another full timeout. A success in
    CLOSED clears the consecutive-failure count.

    ``clock`` is injectable (monotonic seconds) so state transitions are
    unit-testable without sleeping. Thread-safe; `allow()` hands out the
    half-open probe slot to exactly one caller.
    """

    CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"

    def __init__(self, failure_threshold: int = 3,
                 reset_timeout: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout = float(reset_timeout)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            if self._state == self.OPEN and not self._probing and \
                    self._clock() - self._opened_at >= self.reset_timeout:
                return self.HALF_OPEN
            return self._state

    def allow(self) -> bool:
        """May a request go to this dependency right now? In OPEN, the
        first caller after the reset timeout gets the half-open probe
        slot; everyone else keeps getting False until the probe
        reports."""
        with self._lock:
            if self._state == self.HALF_OPEN:
                # a probe is already in flight; everyone else waits for
                # its verdict
                return not self._probing
            if self._state != self.OPEN:
                return True
            if self._probing:
                return False
            if self._clock() - self._opened_at >= self.reset_timeout:
                self._state = self.HALF_OPEN
                self._probing = True
                return True
            return False

    def record_success(self):
        with self._lock:
            self._state = self.CLOSED
            self._failures = 0
            self._probing = False

    def record_failure(self):
        with self._lock:
            self._failures += 1
            if self._state == self.HALF_OPEN or \
                    self._failures >= self.failure_threshold:
                self._state = self.OPEN
                self._opened_at = self._clock()
                self._probing = False


def call_with_watchdog(fn: Callable, timeout: float, what: str = "call",
                       *args, **kwargs):
    """Run `fn(*args, **kwargs)` under a wall-clock watchdog: if it has
    not returned after `timeout` seconds, raise `WatchdogTimeout`.

    The call runs in a daemon worker thread; on timeout the worker is
    abandoned (Python threads cannot be killed), which is exactly the
    right trade for hung control-plane RPCs — the caller gets a typed,
    catchable error instead of blocking forever, and the leaked thread
    dies with the process. `timeout=None` degrades to a plain call.
    """
    if timeout is None:
        return fn(*args, **kwargs)
    result = {}
    done = threading.Event()

    def _run():
        try:
            result["value"] = fn(*args, **kwargs)
        except BaseException as e:          # surfaced in the caller
            result["exc"] = e
        finally:
            done.set()

    t = threading.Thread(target=_run, daemon=True,
                         name=f"watchdog:{what}")
    t.start()
    if not done.wait(timeout):
        raise WatchdogTimeout(f"{what} did not finish within {timeout}s")
    if "exc" in result:
        raise result["exc"]
    return result.get("value")
