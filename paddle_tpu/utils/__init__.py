"""paddle.utils: measurement tooling (op microbench, collective BW probe)
+ misc helpers. Reference: python/paddle/utils/ + the op_tester benchmark
binary (operators/benchmark/op_tester.cc)."""
from . import collective_bench  # noqa: F401
from . import cpp_extension  # noqa: F401
from . import custom_op  # noqa: F401
from . import op_bench  # noqa: F401
from . import retry  # noqa: F401  (fault-tolerance backoff/watchdog)
from .custom_op import register_op  # noqa: F401
from .compat import (OpLastCheckpointChecker, Profiler,  # noqa: F401
                     ProfilerOptions, deprecated, download, get_profiler,
                     require_version, try_import, unique_name)

__all__ = ["op_bench", "collective_bench", "custom_op", "register_op",
           "run_check", "cpp_extension", "dump_config", "deprecated",
           "download", "unique_name", "require_version", "try_import",
           "retry"]


def dump_config(config, path=None):
    """paddle.utils.dump_config (reference utils/__init__.py:29 lists it
    in __all__; the v1 helper printed a trainer config). Renders any
    config-ish object — dict, DistributedStrategy, dataclass, namespace —
    as sorted `key = value` lines; writes to `path` when given, returns
    the text."""
    if hasattr(config, "__dict__") and not isinstance(config, dict):
        items = {k: v for k, v in vars(config).items()
                 if not k.startswith("_")}
    elif isinstance(config, dict):
        items = config
    else:
        items = {"value": config}
    text = "\n".join(f"{k} = {items[k]!r}" for k in sorted(items)) + "\n"
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


def run_check():
    """paddle.utils.run_check parity (reference:
    python/paddle/utils/install_check.py): verify the install by running
    a small computation on the attached backend, with a grad and —
    multi-device — a collective; prints a summary like the reference's
    "PaddlePaddle is installed successfully!"."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle

    devices = jax.devices()
    platform = devices[0].platform
    x = paddle.to_tensor(jnp.ones((4, 4)), stop_gradient=False)
    y = (x @ x).sum()
    y.backward()
    grad_ok = (x.grad is not None
               and bool(jnp.all(jnp.asarray(x.grad._data) == 8.0)))
    if float(y) != 64.0 or not grad_ok:
        raise RuntimeError(
            f"run_check: matmul/grad verification failed on {platform} "
            f"(y={float(y)}, expected 64.0; d(sum(x@x))/dx "
            f"{'== 8 ok' if grad_ok else 'wrong or missing'})")
    n = len(devices)
    # collective check through the framework's OWN mesh/collective layer,
    # single-process only (a process-local array cannot feed a mesh that
    # spans hosts; multihost verification is the DCN bootstrap test's job)
    if n > 1 and jax.process_count() == 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..distributed import collective as C
        from ..distributed import mesh as mesh_mod
        mesh = mesh_mod.build_mesh({"dp": n})
        prev = mesh_mod.get_mesh()
        mesh_mod.set_mesh(mesh)
        try:
            arr = jax.device_put(jnp.ones(n),
                                 NamedSharding(mesh, P("dp")))
            out = C.all_reduce(paddle.Tensor(arr), op=C.ReduceOp.SUM)
            total = float(jnp.asarray(out._data)[0])
        finally:
            mesh_mod.set_mesh(prev)
        if total != n:
            raise RuntimeError(
                f"run_check: all_reduce over {n} devices returned "
                f"{total}, expected {n}")
        print(f"paddle_tpu works on {n} {platform} devices "
              f"(matmul+grad+all_reduce verified).")
    else:
        print(f"paddle_tpu is installed successfully! "
              f"(matmul+grad verified on {platform})")
