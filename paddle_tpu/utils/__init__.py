"""paddle.utils: measurement tooling (op microbench, collective BW probe)
+ misc helpers. Reference: python/paddle/utils/ + the op_tester benchmark
binary (operators/benchmark/op_tester.cc)."""
from . import collective_bench  # noqa: F401
from . import custom_op  # noqa: F401
from . import op_bench  # noqa: F401
from .custom_op import register_op  # noqa: F401

__all__ = ["op_bench", "collective_bench", "custom_op", "register_op"]
