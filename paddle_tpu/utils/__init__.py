"""paddle.utils: measurement tooling (op microbench, collective BW probe)
+ misc helpers. Reference: python/paddle/utils/ + the op_tester benchmark
binary (operators/benchmark/op_tester.cc)."""
from . import op_bench  # noqa: F401
from . import collective_bench  # noqa: F401

__all__ = ["op_bench", "collective_bench"]
