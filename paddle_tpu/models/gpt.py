"""GPT decoder language-model family, TPU-first.

Capability analog of the reference's transformer stack
(/root/reference/python/paddle/nn/layer/transformer.py:115 MultiHeadAttention,
:437 TransformerEncoderLayer) arranged as a pre-LN causal LM (the reference
ships no GPT model class; its GPT-class benchmark configs are external — we
provide the architecture natively since BASELINE.md configs 4-5 are GPT-2
345M / GPT-3 1.3B).

TPU-first design decisions:
  * weights are [in, out] so the hot matmuls are plain `x @ w` on the MXU —
    no transposes in the step function;
  * attention uses F.scaled_dot_product_attention which lowers to the Pallas
    flash kernel on TPU and an XLA composition elsewhere;
  * `gpt_param_shardings` gives the Megatron-style tensor-parallel
    PartitionSpec for every parameter, so `jit(..., in_shardings=...)` over a
    ('dp','tp') mesh runs the model tensor-parallel with XLA inserting the
    all-reduces (the reference reaches multi-device only via graph rewrite
    passes — ir/multi_devices_graph_pass — which XLA subsumes here).
"""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from .. import nn
from .. import ops as F_ops
from ..core.tensor import Tensor
from ..nn import functional as F


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304          # 50257 padded to a multiple of 128 (MXU lane width)
    max_seq_len: int = 1024
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    ffn_mult: int = 4
    dropout: float = 0.0
    dtype: str = "float32"
    moe_experts: int = 0         # >0: MoE FFN with this many experts
    moe_top_k: int = 2
    moe_aux_coef: float = 0.01   # Switch load-balance pressure
    # scan-over-layers: stack block params on a leading [layers] axis and
    # run the stack as one jax.lax.scan step, making HLO size and XLA
    # compile time (near-)invariant in depth. None = auto: on unless MoE
    # (aux losses cannot escape a scan body). False forces the unrolled
    # Python loop (per-block LayerList).
    scan_layers: bool = None
    # tied-head CE kernel choice: None = auto (XLA recompute path below
    # V=64k, Pallas streaming kernel above), True/False forces. True is
    # the memory-optimal setting for big models on one chip — the f32
    # [tokens, V] logits never hit HBM at all (fused_ce.py)
    fused_head_ce: bool = None

    @property
    def head_dim(self):
        return self.hidden // self.heads


def gpt_tiny(**kw):
    return GPTConfig(vocab_size=512, max_seq_len=128, hidden=64, layers=2,
                     heads=4, **kw)


def gpt2_124m(**kw):
    return GPTConfig(hidden=768, layers=12, heads=12, **kw)


def gpt2_345m(**kw):
    return GPTConfig(hidden=1024, layers=24, heads=16, **kw)


def gpt3_1p3b(**kw):
    return GPTConfig(hidden=2048, layers=24, heads=16, max_seq_len=2048, **kw)



def _pp_mm(cd):
    """Matmul helper for the hand-written pipeline blocks: bf16 operands
    when cd is set (AMP), f32 accumulate/output."""
    def mm(a, w):
        if cd is not None:
            return (a.astype(cd) @ w.astype(cd)).astype(jnp.float32)
        return a @ w
    return mm


def _pp_ln(x, g, b, eps):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * g + b


def _pp_dropout(x, key, p):
    """Inverted dropout on raw jnp arrays (the pipeline's pure per-stage
    fns bypass the Tensor-level F.dropout)."""
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)


def _pp_moe(xt, bp, E, K, C, axis_ep=None, axis_tp=None, axis_sp=None):
    """Dense Switch-MoE FFN on raw jnp arrays for the pipeline blocks
    (same routing math as nn/layer/moe.py), in three partitionings:

      axis_ep: each member holds E/n_ep experts; contributions psum over
               'ep' (activations replicated).
      axis_tp: every member holds ALL experts but only Hf/n_tp of each
               expert's hidden dim; partial expert outputs psum over 'tp'
               (Megatron row-parallel w_out).
      axis_sp: experts fully replicated; each member routes its LOCAL
               token shard; the aux statistics pmean over 'sp' BEFORE
               the product so the load-balance value matches the global
               computation exactly (mean-of-products != product-of-means).

    Returns (y [N, H], aux scalar)."""
    if axis_tp is not None and axis_ep is not None:
        raise NotImplementedError(
            "_pp_moe: tp x ep expert sharding in one block is not "
            "supported (pick one; the combine below reduces over a "
            "single axis)")
    N, H = xt.shape
    logits = (xt @ bp["moe.gate_w"]).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    gates_list, onehot_list = [], []
    masked = probs
    for _ in range(K):
        idx = masked.argmax(axis=-1)
        oh = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        gates_list.append((probs * oh).sum(-1))
        onehot_list.append(oh)
        masked = masked * (1.0 - oh)
    flat_oh = jnp.concatenate(onehot_list, 0)
    pos = jnp.cumsum(flat_oh, axis=0) - flat_oh
    keep = (pos < C) * flat_oh
    pos_id = (pos * flat_oh).sum(-1).astype(jnp.int32)
    cap_oh = jax.nn.one_hot(pos_id, C, dtype=jnp.float32)
    gates = jnp.concatenate(gates_list, 0)
    dispatch = keep[:, :, None] * cap_oh[:, None, :]       # [KN, E, C]
    combine = dispatch * gates[:, None, None]

    if axis_ep is not None:
        e_loc = bp["moe.w_in"].shape[0]
        e0 = jax.lax.axis_index(axis_ep) * e_loc
        disp_l = jax.lax.dynamic_slice_in_dim(dispatch, e0, e_loc, 1)
        comb_l = jax.lax.dynamic_slice_in_dim(combine, e0, e_loc, 1)
    else:
        disp_l, comb_l = dispatch, combine

    xrep = jnp.tile(xt, (K, 1)).astype(jnp.float32)
    expert_in = jnp.einsum("nec,nm->ecm", disp_l, xrep)
    hh = jnp.einsum("ecm,emh->ech", expert_in,
                    bp["moe.w_in"].astype(jnp.float32)) \
        + bp["moe.b_in"][:, None, :]
    hh = jax.nn.gelu(hh)
    eout = jnp.einsum("ech,ehm->ecm", hh,
                      bp["moe.w_out"].astype(jnp.float32))
    # combine is linear, so collectives ride the [KN, M] combined output
    # rather than the ~K*cap_f-times-larger [E, C, M] expert tensor;
    # the bias contribution einsum('nec,em->nm') is exact because each
    # dispatched slot receives its expert's bias once
    y_core = jnp.einsum("nec,ecm->nm", comb_l, eout)
    bias_t = jnp.einsum("nec,em->nm", comb_l, bp["moe.b_out"])
    if axis_tp is not None:
        # hidden dim is tp-local: partial combined outputs meet here;
        # bias (replicated) is added once, after the psum
        y = jax.lax.psum(y_core, axis_tp) + bias_t
    elif axis_ep is not None:
        # each member contributes its local experts' outputs AND their
        # bias share; the psum assembles both
        y = jax.lax.psum(y_core + bias_t, axis_ep)
    else:
        y = y_core + bias_t
    y = y.reshape(K, N, H).sum(0)

    frac = onehot_list[0].mean(0)
    mean_p = probs.mean(0)
    if axis_sp is not None:
        # exact global load-balance statistics across sequence shards
        frac = jax.lax.pmean(frac, axis_sp)
        mean_p = jax.lax.pmean(mean_p, axis_sp)
    aux = (frac * mean_p).sum() * E
    return y, aux


def masked_linear_ce(h, weight, labels, ignore_index=-100, fused=None):
    """Tied-head CE via linear_cross_entropy (ops/pallas/fused_ce.py),
    shared by the GPT and BERT heads: the [tokens, vocab] logits are
    never saved as backward residuals — the head matmul is recomputed in
    the VJP (and with fused=True never hits HBM at all). Masking matches
    F.cross_entropy's ignore_index semantics: ignored rows contribute 0
    to the sum and are excluded from the mean's denominator; an
    all-ignored batch yields 0 loss, not 0/0."""
    C = h.shape[-1]
    lab = F_ops.reshape(labels, [-1])
    valid = F_ops.not_equal(lab, F_ops.full_like(lab, ignore_index))
    safe = F_ops.where(valid, lab, F_ops.zeros_like(lab))
    rows = F.linear_cross_entropy(F_ops.reshape(h, [-1, C]), weight, safe,
                                  fused=fused, reduction="none")
    rows = F_ops.where(valid, rows, F_ops.zeros_like(rows))
    n_valid = F_ops.sum(F_ops.cast(valid, "float32"))
    n_valid = F_ops.maximum(n_valid, F_ops.ones_like(n_valid))
    return F_ops.sum(rows) / n_valid


class CausalSelfAttention(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.qkv = nn.Linear(cfg.hidden, 3 * cfg.hidden)
        self.proj = nn.Linear(cfg.hidden, cfg.hidden)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x):
        B, T, C = x.shape
        H, D = self.cfg.heads, self.cfg.head_dim
        qkv = self.qkv(x)                                   # [B,T,3C]
        q, k, v = qkv.chunk(3, axis=-1)
        q = q.reshape([B, T, H, D])
        k = k.reshape([B, T, H, D])
        v = v.reshape([B, T, H, D])
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        out = out.reshape([B, T, C])
        return self.drop(self.proj(out))


class Block(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = nn.LayerNorm(cfg.hidden)
        self.attn = CausalSelfAttention(cfg)
        self.ln2 = nn.LayerNorm(cfg.hidden)
        if cfg.moe_experts > 0:
            # expert-parallel FFN (nn/layer/moe.py; new capability — the
            # reference has no MoE)
            self.moe = nn.MoELayer(cfg.hidden, cfg.ffn_mult * cfg.hidden,
                                   cfg.moe_experts, top_k=cfg.moe_top_k)
        else:
            self.fc1 = nn.Linear(cfg.hidden, cfg.ffn_mult * cfg.hidden)
            self.fc2 = nn.Linear(cfg.ffn_mult * cfg.hidden, cfg.hidden)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        if hasattr(self, "moe"):
            h = self.moe(self.ln2(x))
        else:
            h = self.fc2(F.gelu(self.fc1(self.ln2(x))))
        return x + self.drop(h)


class GPT(nn.Layer):
    """Pre-LN GPT decoder LM. forward(token_ids [B,T]) -> logits [B,T,V]."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        from ..framework import ParamAttr
        from ..nn import initializer as I
        emb_init = ParamAttr(initializer=I.Normal(0.0, 0.02))  # GPT-2 init
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden, weight_attr=emb_init)
        self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden, weight_attr=emb_init)
        self.drop = nn.Dropout(cfg.dropout)
        scan = cfg.scan_layers
        if scan is None:
            scan = cfg.moe_experts == 0  # MoE aux losses can't leave a scan
        elif scan and cfg.moe_experts > 0:
            raise ValueError("scan_layers=True is incompatible with MoE "
                             "blocks (collect_aux_losses cannot cross a "
                             "jax.lax.scan body)")
        per_block = [Block(cfg) for _ in range(cfg.layers)]
        self.blocks = (nn.ScanBlockStack(per_block) if scan
                       else nn.LayerList(per_block))
        self.ln_f = nn.LayerNorm(cfg.hidden)
        # weight tying (lm_head = wte.T) keeps the embedding matmul on-MXU
        # and halves embedding memory, standard for the GPT family.

    def enable_block_recompute(self, flag=True, policy=None):
        """Per-BLOCK activation recomputation (strategy-compiler
        protocol): each transformer block runs under jax.checkpoint, so
        the live set during backward is one block's activations plus the
        per-block boundary residuals — a whole-forward checkpoint keeps
        peak memory unchanged (everything rematerializes at once), which
        is how the 1.3B config OOMed a 16 GB chip. `policy` is a
        jax.checkpoint_policies entry applied per block. The compiler
        sets/restores this around the traced forward only (the flag must
        not leak into later compiles or eager use)."""
        self._recompute_blocks = bool(flag)
        self._recompute_policy = policy
        return self

    def forward_hidden(self, idx):
        """Final-layer-norm hidden states [B,T,C] (everything but the tied
        LM head) — the input the fused linear+CE loss consumes."""
        B, T = idx.shape
        from ..ops.creation import arange
        pos = arange(T, dtype="int64").unsqueeze(0)
        x = self.drop(self.wte(idx) + self.wpe(pos))
        if isinstance(self.blocks, nn.ScanBlockStack):
            self.blocks.set_recompute(
                getattr(self, "_recompute_blocks", False),
                getattr(self, "_recompute_policy", None))
            x = self.blocks(x)
        elif getattr(self, "_recompute_blocks", False):
            from ..distributed.fleet.utils import recompute
            pol = getattr(self, "_recompute_policy", None)
            for blk in self.blocks:
                x = recompute(blk, x, checkpoint_policy=pol)
        else:
            for blk in self.blocks:
                x = blk(x)
        return self.ln_f(x)

    def set_scan_unroll(self, flag=True):
        """Escape hatch (DistributedStrategy.scan_layers = False): run the
        stacked params through a Python loop instead of jax.lax.scan."""
        if isinstance(self.blocks, nn.ScanBlockStack):
            self.blocks.set_unroll(flag)
        return self

    def forward(self, idx):
        x = self.forward_hidden(idx)
        logits = F.linear(x, self.wte.weight.transpose([1, 0]))
        return logits

    def _head_ce(self, h, labels, ignore_index=-100):
        return masked_linear_ce(h, self.wte.weight, labels,
                                ignore_index=ignore_index,
                                fused=self.cfg.fused_head_ce)

    def loss(self, idx, labels, moe_aux_coef=None):
        if moe_aux_coef is None:
            moe_aux_coef = getattr(self.cfg, "moe_aux_coef", 0.01)
        if self.cfg.moe_experts > 0:
            from ..nn.layer.moe import collect_aux_losses
            with collect_aux_losses() as auxes:
                h = self.forward_hidden(idx)
            ce = self._head_ce(h, labels)
            # Switch load-balance pressure so experts don't collapse
            total_aux = auxes[0]
            for a in auxes[1:]:
                total_aux = total_aux + a
            return ce + moe_aux_coef * total_aux / max(len(auxes), 1)
        return self._head_ce(self.forward_hidden(idx), labels)

    def num_params(self) -> int:
        return sum(int(math.prod(p.shape)) for p in self.parameters())

    def flops_per_token(self, seq_len=None) -> int:
        """Train-step (fwd+bwd) FLOPs per token: 6N for the parameter
        matmuls plus the attention score/value matmuls, which contribute
        12 * layers * hidden * seq per token (fwd QK^T and AV are each
        2*T*hidden per token per layer; x3 for fwd+bwd)."""
        n = self.num_params()
        c = self.cfg
        attn = 12 * c.layers * c.hidden * (seq_len or c.max_seq_len)
        return 6 * n + attn

    def param_shardings(self, params, mesh_axis_tp="tp"):
        """Strategy-compiler protocol (fleet/compiler.py `_tp_specs`):
        Megatron tensor-parallel PartitionSpecs for every parameter."""
        return gpt_param_shardings(params, mesh_axis_tp=mesh_axis_tp)

    # -- pipeline-parallel protocol (fleet/compiler.py pipeline branch) ----
    def pipeline_split_params(self, params):
        """Split the flat functional param dict into (embed, [block_i],
        head) for the SPMD pipeline: homogeneous blocks are stacked and
        sharded over 'pp'; embed/head run replicated outside the pipelined
        region (reference program splitter: PipelineOptimizer
        optimizer.py:3718 assigns ops to stages; here the split is by
        construction)."""
        embed = {k: v for k, v in params.items()
                 if k.startswith(("wte.", "wpe."))}
        head = {k: v for k, v in params.items() if k.startswith("ln_f.")}
        blocks = []
        if isinstance(self.blocks, nn.ScanBlockStack):
            # scan layout: params carry stacked "blocks.{rel}" [L, ...]
            # arrays — slice the leading axis back into per-stage dicts
            stacked = {k[len("blocks."):]: v for k, v in params.items()
                       if k.startswith("blocks.")}
            for i in range(self.cfg.layers):
                blocks.append({rel: v[i] for rel, v in stacked.items()})
            return embed, blocks, head
        for i in range(self.cfg.layers):
            pref = f"blocks.{i}."
            blocks.append({k[len(pref):]: v for k, v in params.items()
                           if k.startswith(pref)})
        return embed, blocks, head

    def pipeline_fns(self, ignore_index=-100):
        """Pure (embed_fn, block_fn, head_loss_fn) for the pipeline step.
        block_fn reuses blocks[0] as the shared functional template (all
        blocks are structurally identical; layer i's params are fed in).

        Dropout rides an explicit key: the 1F1B scheduler folds
        (microbatch, global-layer, data-axis ranks) into the step key and
        hands each block call its own subkey (`key_scope` makes the
        Layer-level F.dropout draw from it), so the backward slot's remat
        reproduces the forward's masks exactly — the reference threads
        seeds the same way in its recompute pass
        (fluid/backward.py modify_forward_desc_for_recompute).
        embed_fn's pos_offset shifts wpe lookups for sequence-parallel
        shards (local T/sp window into the global positions)."""
        from ..core import random as random_mod
        from ..framework import functional_call
        from ..ops.pallas.fused_ce import linear_cross_entropy
        blk0 = self.blocks[0]
        p_drop = float(self.cfg.dropout)

        def embed_fn(ep, ids, pos_offset=0, key=None):
            T = ids.shape[-1]
            pos = jnp.arange(T) + pos_offset
            x = ep["wte.weight"][ids] + ep["wpe.weight"][pos]
            # self.training read at trace time — the same capture moment
            # as blk0.training inside functional_call, so embed and block
            # dropout always agree on train/eval mode
            if p_drop > 0 and key is not None and self.training:
                x = _pp_dropout(x, key, p_drop)
            return x

        emits_aux = self.cfg.moe_experts > 0

        def _call_block(bp, h, key):
            """One block through functional_call; MoE configs also return
            the Switch load-balance aux (the 1F1B scheduler threads it
            into the objective — reference analog: the aux-loss fetch the
            pipeline trainer skips, here actually propagated)."""
            import contextlib

            ctx = random_mod.key_scope(key) if key is not None \
                else contextlib.nullcontext()
            if emits_aux:
                from ..nn.layer.moe import collect_aux_losses
                with collect_aux_losses() as auxes, ctx:
                    out, _ = functional_call(blk0, bp, {}, h,
                                             mutable_state=False)
                total = auxes[0]
                for a in auxes[1:]:
                    total = total + a
                total = total._data if hasattr(total, "_data") else total
                return out, total
            with ctx:
                out, _ = functional_call(blk0, bp, {}, h,
                                         mutable_state=False)
            return out

        if p_drop > 0:
            def block_fn(bp, h, key=None):
                if key is None and blk0.training:
                    # no key in TRAIN mode -> trace-time constant masks;
                    # refuse loudly (eval mode draws no dropout and is
                    # fine keyless — the pipelined eval path)
                    raise NotImplementedError(
                        "GPT pipeline block with dropout > 0 needs the "
                        "scheduler to thread a PRNG key (use the "
                        "fleet-compiled train step)")
                return _call_block(bp, h, key)
        else:
            def block_fn(bp, h):
                return _call_block(bp, h, None)

        eps = self.ln_f._epsilon

        def head_loss_fn(hp, ep, h, labels):
            """Returns (loss_sum, valid_token_count) so the caller can form
            the GLOBAL masked mean over all microbatches — a per-microbatch
            mean-of-means would weight unevenly-padded microbatches
            differently from the sequential path."""
            g, b = hp["ln_f.weight"], hp["ln_f.bias"]
            mu = h.mean(-1, keepdims=True)
            var = ((h - mu) ** 2).mean(-1, keepdims=True)
            hn = (h - mu) / jnp.sqrt(var + eps) * g + b
            H = hn.shape[-1]
            lab = labels.reshape(-1).astype(jnp.int32)
            valid = lab != ignore_index
            # tied head via the fused linear+CE op (same ignore_index
            # masking as F.cross_entropy: padded rows contribute 0)
            rows = linear_cross_entropy(
                hn.reshape(-1, H), ep["wte.weight"],
                jnp.where(valid, lab, 0))
            rows = jnp.where(valid, rows, 0.0)
            return rows.sum(), valid.astype(jnp.float32).sum()

        # label-only count for the scheduler's aux-gradient pre-scaling
        head_loss_fn.valid_count = lambda labels: (
            labels.reshape(-1).astype(jnp.int32) != ignore_index
        ).astype(jnp.float32).sum()
        return embed_fn, block_fn, head_loss_fn

    @property
    def pipeline_block_emits_aux(self):
        """True when pipeline_fns' block_fn returns (h, aux) — MoE
        configs carry the Switch load-balance loss through the 1F1B
        scheduler."""
        return self.cfg.moe_experts > 0

    # -- manual-tp pipeline protocol (pp x tp composition) -----------------
    # The SPMD pipeline runs inside a shard_map where every mesh axis is
    # manual, so tensor parallelism inside a stage cannot rely on GSPMD:
    # the packed qkv matrix must be physically split per head-group and
    # the two Megatron reductions (after attn-proj and after fc2) are
    # explicit psums over 'tp'. Reference analog: the hand-inserted
    # c_allreduce ops a Megatron program rewrite would emit.

    TP_SPLIT_KEYS = ("q_w", "q_b", "k_w", "k_b", "v_w", "v_b")

    @staticmethod
    def split_block_params_tp(bp):
        """One block's params -> manual-tp layout: packed qkv split into
        q/k/v so a last-dim shard holds whole heads."""
        import numpy as _np
        qkv_w = _np.asarray(bp["attn.qkv.weight"])     # [H, 3H]
        qkv_b = _np.asarray(bp["attn.qkv.bias"])       # [3H]
        q_w, k_w, v_w = _np.split(qkv_w, 3, axis=1)
        q_b, k_b, v_b = _np.split(qkv_b, 3)
        out = {k: v for k, v in bp.items()
               if not k.startswith("attn.qkv.")}
        out.update({"q_w": q_w, "k_w": k_w, "v_w": v_w,
                    "q_b": q_b, "k_b": k_b, "v_b": v_b})
        return out

    @staticmethod
    def merge_block_params_tp(split):
        """Inverse of split_block_params_tp (for write_back)."""
        import numpy as _np
        out = {k: v for k, v in split.items()
               if k not in GPT.TP_SPLIT_KEYS}
        out["attn.qkv.weight"] = _np.concatenate(
            [split["q_w"], split["k_w"], split["v_w"]], axis=1)
        out["attn.qkv.bias"] = _np.concatenate(
            [split["q_b"], split["k_b"], split["v_b"]])
        return out

    @staticmethod
    def block_tp_specs(axis_pp="pp", axis_tp="tp"):
        """Stacked-layout PartitionSpecs for the split-tp block params
        ([L, ...] leading layer dim over pp; Megatron col/row over tp)."""
        from jax.sharding import PartitionSpec as P
        return {
            "ln1.weight": P(axis_pp, None), "ln1.bias": P(axis_pp, None),
            "ln2.weight": P(axis_pp, None), "ln2.bias": P(axis_pp, None),
            "q_w": P(axis_pp, None, axis_tp), "q_b": P(axis_pp, axis_tp),
            "k_w": P(axis_pp, None, axis_tp), "k_b": P(axis_pp, axis_tp),
            "v_w": P(axis_pp, None, axis_tp), "v_b": P(axis_pp, axis_tp),
            "attn.proj.weight": P(axis_pp, axis_tp, None),
            "attn.proj.bias": P(axis_pp, None),
            "fc1.weight": P(axis_pp, None, axis_tp),
            "fc1.bias": P(axis_pp, axis_tp),
            "fc2.weight": P(axis_pp, axis_tp, None),
            "fc2.bias": P(axis_pp, None),
            # MoE under tp: every member holds all experts, hidden dim
            # sharded (Megatron column/row split per expert); router and
            # output biases replicate
            "moe.gate_w": P(axis_pp, None, None),
            "moe.w_in": P(axis_pp, None, None, axis_tp),   # [L,E,M,Hf]
            "moe.b_in": P(axis_pp, None, axis_tp),
            "moe.w_out": P(axis_pp, None, axis_tp, None),  # [L,E,Hf,M]
            "moe.b_out": P(axis_pp, None, None),
        }

    def pipeline_block_fn_tp(self, axis_tp="tp", compute_dtype=None,
                             with_aux=False, axis_sp=None, impl="ring"):
        """block_fn for the manual-tp pipeline: local head-group attention
        + Megatron MLP with explicit psums over `axis_tp`. Operates on the
        split layout from split_block_params_tp (local tp shards).

        With `axis_sp` set this is the pp x tp x SP block (the v5p-64
        long-context mesh; VERDICT r4 Next #7): h is the LOCAL sequence
        shard [B, T/sp, H] and attention runs as ring/Ulysses over
        `axis_sp` on the local head group — attention is per-head, so
        the sp ring composes with the tp head split directly; LN/MLP are
        sequence-elementwise and keep the same tp psums.

        MoE configs replace the MLP with the Switch FFN partitioned the
        Megatron way: every member holds all experts but only Hf/n_tp of
        each expert's hidden dim (block_tp_specs moe.* entries), partial
        expert outputs psum over 'tp' (_pp_moe axis_tp; with axis_sp the
        routing stats additionally fold over the sequence shards).
        Routing runs on the tp-replicated stream, so members agree
        without a collective; with_aux threads the load-balance aux to
        the scheduler.

        compute_dtype="bfloat16": matmul/einsum operands cast to bf16 (the
        AMP-O1 treatment — raw jnp ops here bypass the autocast dispatcher
        hook, so the cast must be explicit); LN stats, softmax and the
        residual stream stay f32.

        Dropout (Block's two sites: after attn-proj, after fc2) rides the
        scheduler-threaded key, folded by the sp rank when axis_sp is set
        (different tokens per shard) and NEVER by tp rank: the residual
        stream is replicated over 'tp', so every member must draw the
        identical mask or the manual psums stop agreeing (the scheduler's
        fold_data_axes enforces both)."""
        attn_impl = None
        if axis_sp is not None:
            from ..distributed.sequence_parallel import (ring_attention,
                                                         ulysses_attention)
            impls = {"ring": ring_attention, "ulysses": ulysses_attention}
            if impl not in impls:
                raise ValueError(
                    f"sequence_parallel impl must be 'ring' or "
                    f"'ulysses', got {impl!r}")
            attn_impl = impls[impl]
        is_moe = self.cfg.moe_experts > 0
        if with_aux and not is_moe:
            raise ValueError("with_aux needs a MoE config")
        E = self.cfg.moe_experts
        K = self.cfg.moe_top_k if is_moe else 0
        cap_f = self.blocks[0].moe.capacity_factor if is_moe else 0.0
        D = self.cfg.head_dim
        eps1 = self.blocks[0].ln1._epsilon
        eps2 = self.blocks[0].ln2._epsilon
        cd = jnp.bfloat16 if compute_dtype in ("bfloat16", "bf16",
                                               jnp.bfloat16) else None
        mm, ln = _pp_mm(cd), _pp_ln
        p_drop = float(self.cfg.dropout)
        gpt_self = self

        def _drop(x, key, site):
            if p_drop <= 0 or key is None or not gpt_self.training:
                return x
            return _pp_dropout(x, jax.random.fold_in(key, site), p_drop)

        def _block_core(bp, h, key):
            B, T, H = h.shape                   # T is T/sp under axis_sp
            h1 = ln(h, bp["ln1.weight"], bp["ln1.bias"], eps1)
            q = mm(h1, bp["q_w"]) + bp["q_b"]   # [B,T,H/ntp] local heads
            k = mm(h1, bp["k_w"]) + bp["k_b"]
            v = mm(h1, bp["v_w"]) + bp["v_b"]
            nloc = q.shape[-1] // D
            q = q.reshape(B, T, nloc, D)
            k = k.reshape(B, T, nloc, D)
            v = v.reshape(B, T, nloc, D)
            if cd is not None:
                q, k, v = q.astype(cd), k.astype(cd), v.astype(cd)
            if attn_impl is not None:
                o = attn_impl(q, k, v, axis=axis_sp, causal=True) \
                    .reshape(B, T, -1).astype(jnp.float32)
            else:
                # causal attention on the local head group — same op
                # order as F.scaled_dot_product_attention's XLA core
                # (attention.py _sdpa_xla) so pp x tp matches the
                # sequential loss closely
                s = jnp.einsum("bqhd,bkhd->bhqk", q, k) \
                    * (1.0 / math.sqrt(D))
                s = s.astype(jnp.float32)
                causal = jnp.tril(jnp.ones((T, T), bool))
                s = jnp.where(causal[None, None], s, -1e30)
                p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
                o = jnp.einsum("bhqk,bkhd->bqhd", p, v) \
                    .reshape(B, T, -1).astype(jnp.float32)
            # row-parallel proj: partial sums meet across head groups
            att = jax.lax.psum(mm(o, bp["attn.proj.weight"]), axis_tp) \
                + bp["attn.proj.bias"]
            h = h + _drop(att, key, 0)
            h2 = ln(h, bp["ln2.weight"], bp["ln2.bias"], eps2)
            if is_moe:
                N = B * T
                C = max(int(math.ceil(cap_f * N * K / E)), 1)
                y, aux = _pp_moe(h2.reshape(N, H), bp, E, K, C,
                                 axis_tp=axis_tp, axis_sp=axis_sp)
                out = h + _drop(y.reshape(B, T, H).astype(h.dtype), key, 1)
                return (out, aux) if with_aux else out
            m = jax.nn.gelu(mm(h2, bp["fc1.weight"]) + bp["fc1.bias"],
                            approximate=False)   # Block uses exact gelu
            mo = jax.lax.psum(mm(m, bp["fc2.weight"]), axis_tp) \
                + bp["fc2.bias"]
            return h + _drop(mo, key, 1)

        if p_drop > 0:
            def block_fn(bp, h, key=None):
                return _block_core(bp, h, key)
        else:
            def block_fn(bp, h):
                return _block_core(bp, h, None)

        return block_fn


    def pipeline_block_fn_tp_sp(self, axis_tp="tp", axis_sp="sp",
                                impl="ring", compute_dtype=None,
                                with_aux=False):
        """pp x tp x sp block (strategy-compiler protocol name): the tp
        block with ring/Ulysses attention over `axis_sp` — one
        implementation, see pipeline_block_fn_tp's axis_sp mode."""
        return self.pipeline_block_fn_tp(
            axis_tp=axis_tp, compute_dtype=compute_dtype,
            with_aux=with_aux, axis_sp=axis_sp, impl=impl)

    def pipeline_block_fn_sp(self, axis_sp="sp", impl="ring",
                             compute_dtype=None, with_aux=False):
        """block_fn for the pipeline x sequence-parallel mesh: the block
        sees the LOCAL sequence shard [B, T/sp, C]; attention runs as
        ring attention (K/V rotation over `axis_sp`) or Ulysses — both
        shard_map-inner (distributed/sequence_parallel.py), which is what
        the pipeline's all-manual region requires. LN/MLP are sequence-
        elementwise, so they need no collectives at all.

        Dropout rides the scheduler key, which the 1F1B scheduler FOLDS
        by the sp rank (pipeline_value_and_grad's data-axis folding) —
        each shard holds different tokens, so masks must decorrelate.

        MoE: experts replicate; each member routes its local tokens with
        local capacity (_pp_moe axis_sp folds the load-balance stats
        across shards so the aux matches the global value exactly)."""
        from ..distributed.sequence_parallel import (ring_attention,
                                                     ulysses_attention)
        impls = {"ring": ring_attention, "ulysses": ulysses_attention}
        if impl not in impls:
            raise ValueError(
                f"sequence_parallel impl must be 'ring' or 'ulysses', "
                f"got {impl!r}")
        attn_impl = impls[impl]
        is_moe = self.cfg.moe_experts > 0
        if with_aux and not is_moe:
            raise ValueError("with_aux needs a MoE config")
        E = self.cfg.moe_experts
        K = self.cfg.moe_top_k if is_moe else 0
        cap_f = self.blocks[0].moe.capacity_factor if is_moe else 0.0
        D = self.cfg.head_dim
        eps1 = self.blocks[0].ln1._epsilon
        eps2 = self.blocks[0].ln2._epsilon
        cd = jnp.bfloat16 if compute_dtype in ("bfloat16", "bf16",
                                               jnp.bfloat16) else None
        mm, ln = _pp_mm(cd), _pp_ln
        p_drop = float(self.cfg.dropout)
        gpt_self = self

        def _drop(x, key, site):
            if p_drop <= 0 or key is None or not gpt_self.training:
                return x
            return _pp_dropout(x, jax.random.fold_in(key, site), p_drop)

        def _core(bp, h, key):
            B, Tl, H = h.shape
            h1 = ln(h, bp["ln1.weight"], bp["ln1.bias"], eps1)
            qkv = mm(h1, bp["attn.qkv.weight"]) + bp["attn.qkv.bias"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            nh = H // D
            q = q.reshape(B, Tl, nh, D)
            k = k.reshape(B, Tl, nh, D)
            v = v.reshape(B, Tl, nh, D)
            if cd is not None:
                q, k, v = q.astype(cd), k.astype(cd), v.astype(cd)
            o = attn_impl(q, k, v, axis=axis_sp, causal=True)
            o = o.reshape(B, Tl, H).astype(jnp.float32)
            att = mm(o, bp["attn.proj.weight"]) + bp["attn.proj.bias"]
            h = h + _drop(att, key, 0)
            h2 = ln(h, bp["ln2.weight"], bp["ln2.bias"], eps2)
            if is_moe:
                N = B * Tl
                C = max(int(math.ceil(cap_f * N * K / E)), 1)
                y, aux = _pp_moe(h2.reshape(N, H), bp, E, K, C,
                                 axis_sp=axis_sp)
                out = h + _drop(y.reshape(B, Tl, H).astype(h.dtype),
                                key, 1)
                return (out, aux) if with_aux else out
            m = jax.nn.gelu(mm(h2, bp["fc1.weight"]) + bp["fc1.bias"],
                            approximate=False)
            return h + _drop(mm(m, bp["fc2.weight"]) + bp["fc2.bias"],
                             key, 1)

        if p_drop > 0:
            def block_fn(bp, h, key=None):
                return _core(bp, h, key)
        else:
            def block_fn(bp, h):
                return _core(bp, h, None)

        return block_fn


    @staticmethod
    def block_ep_specs(axis_pp="pp", axis_ep="ep"):
        """Stacked-layout PartitionSpecs for a MoE block under manual
        expert parallelism: expert banks shard their E dim over 'ep',
        everything else replicates (attention is untouched by ep)."""
        from jax.sharding import PartitionSpec as P

        def expert(ndim):
            return P(axis_pp, axis_ep, *([None] * (ndim - 2)))

        return {
            "ln1.weight": P(axis_pp, None), "ln1.bias": P(axis_pp, None),
            "ln2.weight": P(axis_pp, None), "ln2.bias": P(axis_pp, None),
            "attn.qkv.weight": P(axis_pp, None, None),
            "attn.qkv.bias": P(axis_pp, None),
            "attn.proj.weight": P(axis_pp, None, None),
            "attn.proj.bias": P(axis_pp, None),
            "moe.gate_w": P(axis_pp, None, None),
            "moe.w_in": expert(4),   # [L, E, M, H]
            "moe.b_in": expert(3),
            "moe.w_out": expert(4),
            "moe.b_out": expert(3),
        }

    def pipeline_block_fn_ep(self, axis_ep="ep", compute_dtype=None,
                             with_aux=False, axis_sp=None, impl="ring"):
        """block_fn for pipeline x expert parallelism: activations are
        REPLICATED across 'ep' members, each member runs only its local
        expert slab (E/n_ep experts of the stacked bank), and one psum
        over 'ep' sums the per-expert contributions — the manual form of
        the GSPMD einsum dispatch in nn/layer/moe.py.

        With `axis_sp` set this is the pp x sp x EP block (formerly an
        explicit refusal): the stream is the LOCAL sequence shard, the
        attention is ring/Ulysses over `axis_sp`, each member routes its
        local tokens with local capacity, and _pp_moe folds the
        load-balance statistics over 'sp' (exact global aux) while the
        expert-slab psum stays over 'ep'.

        with_aux=True: the block also returns the Switch load-balance
        aux (E * sum_e frac_tokens_e * mean_prob_e, same formula as
        nn/layer/moe.py) — the 1F1B scheduler threads it into the
        objective, so expert-collapse pressure IS applied on the
        pipeline path."""
        if self.cfg.moe_experts <= 0:
            raise ValueError("pipeline_block_fn_ep requires a MoE config "
                             "(GPTConfig.moe_experts > 0)")
        attn_impl = None
        if axis_sp is not None:
            from ..distributed.sequence_parallel import (
                ring_attention, ulysses_attention)
            impls = {"ring": ring_attention, "ulysses": ulysses_attention}
            if impl not in impls:
                raise ValueError(
                    f"sequence_parallel impl must be 'ring' or "
                    f"'ulysses', got {impl!r}")
            attn_impl = impls[impl]
        D = self.cfg.head_dim
        E = self.cfg.moe_experts
        K = self.cfg.moe_top_k
        cap_f = self.blocks[0].moe.capacity_factor
        eps1 = self.blocks[0].ln1._epsilon
        eps2 = self.blocks[0].ln2._epsilon
        cd = jnp.bfloat16 if compute_dtype in ("bfloat16", "bf16",
                                               jnp.bfloat16) else None
        mm, ln = _pp_mm(cd), _pp_ln
        p_drop = float(self.cfg.dropout)
        gpt_self = self

        def _drop(x, key, site):
            # key identical across 'ep' members (the scheduler folds only
            # data axes): the residual stream is replicated over ep, so
            # every member must draw the same mask or the psum breaks
            if p_drop <= 0 or key is None or not gpt_self.training:
                return x
            return _pp_dropout(x, jax.random.fold_in(key, site), p_drop)

        def _core(bp, h, key):
            B, T, H = h.shape
            h1 = ln(h, bp["ln1.weight"], bp["ln1.bias"], eps1)
            qkv = mm(h1, bp["attn.qkv.weight"]) + bp["attn.qkv.bias"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            nh = H // D
            q = q.reshape(B, T, nh, D)
            k = k.reshape(B, T, nh, D)
            v = v.reshape(B, T, nh, D)
            if attn_impl is not None:
                if cd is not None:   # AMP: ring traffic + matmuls in bf16
                    q, k, v = q.astype(cd), k.astype(cd), v.astype(cd)
                o = attn_impl(q, k, v, axis=axis_sp, causal=True) \
                    .reshape(B, T, H).astype(jnp.float32)
            else:
                s = jnp.einsum("bqhd,bkhd->bhqk", q, k) \
                    * (1.0 / math.sqrt(D))
                s = s.astype(jnp.float32)
                causal = jnp.tril(jnp.ones((T, T), bool))
                s = jnp.where(causal[None, None], s, -1e30)
                p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
                o = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, T, H) \
                    .astype(jnp.float32)
            att = mm(o, bp["attn.proj.weight"]) + bp["attn.proj.bias"]
            h = h + _drop(att, key, 0)

            # --- MoE FFN, manual ep: full routing, local expert slab ---
            h2 = ln(h, bp["ln2.weight"], bp["ln2.bias"], eps2)
            N = B * T
            C = max(int(math.ceil(cap_f * N * K / E)), 1)
            y, aux = _pp_moe(h2.reshape(N, H), bp, E, K, C,
                             axis_ep=axis_ep, axis_sp=axis_sp)
            out = h + _drop(y.reshape(B, T, H).astype(h.dtype), key, 1)
            # routing is replicated over 'ep' so every member computes
            # the identical aux value
            return (out, aux) if with_aux else out

        if p_drop > 0:
            def block_fn(bp, h, key=None):
                return _core(bp, h, key)
        else:
            def block_fn(bp, h):
                return _core(bp, h, None)

        return block_fn


def gpt_param_shardings(params, mesh_axis_tp="tp"):
    """Megatron-style TP PartitionSpecs keyed by the functional param dict
    names produced by `framework.functional_call` on a GPT instance.

    Column-parallel (shard output dim): qkv and ffn-in weights.
    Row-parallel (shard input dim): attn proj and ffn-out weights — XLA
    inserts the psum where the partial sums meet, exactly the Megatron
    f/g collectives, but compiler-derived instead of hand-written.
    Embeddings shard over vocab/feature rows.
    """
    import re

    from jax.sharding import PartitionSpec as P
    specs = {}
    for name, v in params.items():
        ndim = len(v.shape)
        # scan layout: "blocks.{rel}" (no block index) carries a leading
        # [layers] scan axis — shard the per-block dims, replicate layers
        stacked = (name.startswith("blocks.")
                   and not re.match(r"blocks\.\d+\.", name))
        if stacked:
            ndim -= 1
        if ".moe." in name and name.rsplit(".", 1)[-1] in (
                "w_in", "b_in", "w_out", "b_out"):
            spec = P("ep", *([None] * (ndim - 1)))       # expert parallel
        elif "qkv.weight" in name or "fc1.weight" in name:
            spec = P(None, mesh_axis_tp)                 # column parallel
        elif "qkv.bias" in name or "fc1.bias" in name:
            spec = P(mesh_axis_tp)
        elif "proj.weight" in name or "fc2.weight" in name:
            spec = P(mesh_axis_tp, None)                 # row parallel
        elif "wte.weight" in name:
            spec = P(mesh_axis_tp, None)                 # vocab parallel
        elif ndim >= 2:
            spec = P(*([None] * ndim))
        else:
            spec = P()                                   # replicate ln/bias
        specs[name] = P(None, *spec) if stacked else spec
    return specs


# ---------------------------------------------------------------------------
# Incremental (KV-cache) decode forward — inference/decode.py's compute core
# ---------------------------------------------------------------------------

def split_decode_params(params, cfg: GPTConfig):
    """Split a GPT functional param dict into (embed, [block_i], head)
    for the decode fns, accepting BOTH parameter layouts a GPT instance
    can produce: per-block indexed names ("blocks.3.attn.qkv.weight")
    and the scan-stacked layout ("blocks.attn.qkv.weight" with a leading
    [layers] axis). Slicing the stack here keeps the decode step a plain
    Python loop over layers — each step traces once per shape rung, so
    scan's compile-time advantage does not apply."""
    import re
    embed = {k: v for k, v in params.items()
             if k.startswith(("wte.", "wpe."))}
    head = {k: v for k, v in params.items() if k.startswith("ln_f.")}
    stacked = {k[len("blocks."):]: v for k, v in params.items()
               if k.startswith("blocks.")
               and not re.match(r"blocks\.\d+\.", k)}
    blocks = []
    if stacked:
        for i in range(cfg.layers):
            blocks.append({rel: v[i] for rel, v in stacked.items()})
    else:
        for i in range(cfg.layers):
            pref = f"blocks.{i}."
            blocks.append({k[len(pref):]: v for k, v in params.items()
                           if k.startswith(pref)})
    return embed, blocks, head


def _qmm(bp, name, x):
    """Weight matmul over a possibly PTQ-quantized decode param dict.

    `quant.ptq.quantize_params` stores an int8 weight under its original
    key with an fp32 per-output-channel scale sibling at `name::scale`.
    When the sibling is absent this is literally `x @ w` — the fp32 path
    traces identically to unquantized code — otherwise the matmul routes
    through the fused dequant kernel (`ops.pallas.quant_matmul`)."""
    s = bp.get(name + "::scale")
    if s is None:
        return x @ bp[name]
    from ..ops.pallas.quant_matmul import int8_weight_matmul
    return int8_weight_matmul(x, bp[name], s)


# An fp32 KV pool is a bare [layers, P, page_tokens, heads, head_dim]
# array; the int8 pool (quant/kv.py) is the (data int8, scale f32)
# pytree with one scale per (layer, page, row, head). The helpers below
# branch on that structure at trace time, so every paged decode fn
# serves both pool dtypes from one code path and the fp32 trace is
# byte-identical to the pre-quantization implementation.

def _kv_pool_write(pool, li, page_idx, offset, rows):
    """Scatter fresh fp32 K/V rows at [li, page_idx, offset] (`li` may
    be `slice(None)` for all-layer scatters); int8 pools quantize the
    rows per (row, head) inside the same executable."""
    if isinstance(pool, tuple):
        from ..quant.kv import quantize_kv
        data, scale = pool
        q, s = quantize_kv(rows)
        return (data.at[li, page_idx, offset].set(q),
                scale.at[li, page_idx, offset].set(s))
    return pool.at[li, page_idx, offset].set(rows)


def _kv_pool_layer(pool, li):
    """Layer `li`'s pool view: bare array slice, or (data, scale)."""
    if isinstance(pool, tuple):
        return pool[0][li], pool[1][li]
    return pool[li]


def _kv_pool_take(pool, tables, axis):
    """Block-table gather of pool pages as fp32 rows (dequantizing an
    int8 pool's gathered panel in the same expression)."""
    if isinstance(pool, tuple):
        return (jnp.take(pool[0], tables, axis=axis).astype(jnp.float32)
                * jnp.take(pool[1], tables, axis=axis)[..., None])
    return jnp.take(pool, tables, axis=axis)


def _paged_attend(q, k_layer, v_layer, tables, lengths):
    """Paged decode attention over one layer's pool view, fused-dequant
    variant when the pool is int8."""
    if isinstance(k_layer, tuple):
        from ..ops.pallas.decode_attention import paged_decode_attention_quant
        return paged_decode_attention_quant(
            q, k_layer[0], k_layer[1], v_layer[0], v_layer[1],
            tables, lengths)
    from ..ops.pallas.decode_attention import paged_decode_attention
    return paged_decode_attention(q, k_layer, v_layer, tables, lengths)


def gpt_decode_fns(cfg: GPTConfig, eps: float = 1e-5):
    """Pure `(prefill, decode_step)` over the functional param dict.

    prefill(params, tokens [B,T] i32, lens [B] i32)
        -> (logits [B,V] at each row's position lens-1,
            k, v    [layers, B, T, heads, head_dim])
    decode_step(params, k, v, last_tok [B] i32, cache_len [B] i32)
        -> (logits [B,V], k, v) — writes the new token's K/V at row
           index cache_len via lax.dynamic_update_slice, attends the
           masked prefix 0..cache_len, so one executable serves every
           occupancy of a (batch-rung x kv-capacity-rung) bucket.

    The math mirrors the pipeline block cores above (same op order as
    F.scaled_dot_product_attention's XLA path: f32 scores, -1e30 mask,
    f32 softmax, exact gelu), so prefill+N steps reproduce the full
    forward within fp32 tolerance — tests/test_decode.py enforces it.
    Rows past `lens` / inactive slots compute garbage that causality and
    the cache_len mask keep out of every live row's logits.
    """
    if cfg.moe_experts > 0:
        raise NotImplementedError(
            "gpt_decode_fns: MoE blocks have no KV-decode path yet")
    D = cfg.head_dim
    nh = cfg.heads
    scale = 1.0 / math.sqrt(D)

    def _ffn(bp, x):
        h2 = _pp_ln(x, bp["ln2.weight"], bp["ln2.bias"], eps)
        m = jax.nn.gelu(_qmm(bp, "fc1.weight", h2) + bp["fc1.bias"],
                        approximate=False)
        return x + _qmm(bp, "fc2.weight", m) + bp["fc2.bias"]

    def prefill(params, tokens, lens):
        embed, blocks, head = split_decode_params(params, cfg)
        B, T = tokens.shape
        pos = jnp.arange(T, dtype=jnp.int32)
        x = embed["wte.weight"][tokens] + embed["wpe.weight"][pos]
        ks, vs = [], []
        causal = jnp.tril(jnp.ones((T, T), bool))
        for bp in blocks:
            h1 = _pp_ln(x, bp["ln1.weight"], bp["ln1.bias"], eps)
            qkv = _qmm(bp, "attn.qkv.weight", h1) + bp["attn.qkv.bias"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, T, nh, D)
            k = k.reshape(B, T, nh, D)
            v = v.reshape(B, T, nh, D)
            ks.append(k)
            vs.append(v)
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
            s = s.astype(jnp.float32)
            s = jnp.where(causal[None, None], s, -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
            o = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, T, -1)
            x = x + _qmm(bp, "attn.proj.weight", o) + bp["attn.proj.bias"]
            x = _ffn(bp, x)
        xf = _pp_ln(x, head["ln_f.weight"], head["ln_f.bias"], eps)
        last = jnp.clip(lens.astype(jnp.int32) - 1, 0, T - 1)
        xl = jnp.take_along_axis(xf, last[:, None, None], axis=1)[:, 0]
        logits = xl @ embed["wte.weight"].T
        return logits, jnp.stack(ks), jnp.stack(vs)

    def _write_row(cache, new, p):
        # cache [cap, nh, D]; new [nh, D]; p scalar row index
        z = jnp.zeros((), p.dtype)
        return jax.lax.dynamic_update_slice(cache, new[None], (p, z, z))

    def decode_step(params, k_cache, v_cache, last_tok, cache_len):
        from ..ops.pallas.decode_attention import decode_attention
        embed, blocks, head = split_decode_params(params, cfg)
        B = last_tok.shape[0]
        pos = jnp.clip(cache_len.astype(jnp.int32), 0,
                       cfg.max_seq_len - 1)
        x = embed["wte.weight"][last_tok] + embed["wpe.weight"][pos]
        k_out, v_out = [], []
        lengths = pos + 1                 # the row just written is live
        for i, bp in enumerate(blocks):
            h1 = _pp_ln(x, bp["ln1.weight"], bp["ln1.bias"], eps)
            qkv = _qmm(bp, "attn.qkv.weight", h1) + bp["attn.qkv.bias"]
            q, k_new, v_new = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, nh, D)
            k_new = k_new.reshape(B, nh, D)
            v_new = v_new.reshape(B, nh, D)
            ki = jax.vmap(_write_row)(k_cache[i], k_new, pos)
            vi = jax.vmap(_write_row)(v_cache[i], v_new, pos)
            k_out.append(ki)
            v_out.append(vi)
            o = decode_attention(q, ki, vi, lengths).reshape(B, -1)
            x = x + _qmm(bp, "attn.proj.weight", o) + bp["attn.proj.bias"]
            x = _ffn(bp, x)
        xf = _pp_ln(x, head["ln_f.weight"], head["ln_f.bias"], eps)
        logits = xf @ embed["wte.weight"].T
        return logits, jnp.stack(k_out), jnp.stack(v_out)

    return prefill, decode_step


def gpt_paged_decode_fns(cfg: GPTConfig, eps: float = 1e-5,
                         page_tokens: int = 16):
    """Pure `(prefill, paged_step)` over a PAGED KV cache.

    `prefill` is gpt_decode_fns' — the contiguous panel it returns is
    written into pool pages by the engine. The step replaces the
    per-slot contiguous panel with a shared page pool + block tables:

    paged_step(params,
               k_pool, v_pool [layers, P, page_tokens, heads, head_dim],
               tables   [B, W] int32 (unused entries -> null page 0),
               last_tok [B] int32,
               cache_len [B] int32)
        -> (logits [B,V], k_pool, v_pool)

    The new token's K/V lands at page tables[b, cache_len//pt], row
    cache_len%pt, via one advanced-index scatter per layer (padded batch
    rows carry all-null tables, so their garbage writes fall into the
    reserved scratch page); attention walks the block table through
    `ops.pallas.decode_attention.paged_decode_attention`. One executable
    serves every occupancy of a (batch-rung x page-rung) bucket, and —
    unlike the contiguous pool — capacity growth is just a wider block
    table, never a cache copy.
    """
    if cfg.moe_experts > 0:
        raise NotImplementedError(
            "gpt_paged_decode_fns: MoE blocks have no KV-decode path yet")
    D = cfg.head_dim
    nh = cfg.heads
    pt = int(page_tokens)

    def _ffn(bp, x):
        h2 = _pp_ln(x, bp["ln2.weight"], bp["ln2.bias"], eps)
        m = jax.nn.gelu(_qmm(bp, "fc1.weight", h2) + bp["fc1.bias"],
                        approximate=False)
        return x + _qmm(bp, "fc2.weight", m) + bp["fc2.bias"]

    def paged_step(params, k_pool, v_pool, tables, last_tok, cache_len):
        embed, blocks, head = split_decode_params(params, cfg)
        B = last_tok.shape[0]
        W = tables.shape[1]
        pos = jnp.clip(cache_len.astype(jnp.int32), 0,
                       cfg.max_seq_len - 1)
        x = embed["wte.weight"][last_tok] + embed["wpe.weight"][pos]
        page_idx = jnp.take_along_axis(
            tables, jnp.minimum(pos // pt, W - 1)[:, None], axis=1)[:, 0]
        offset = pos % pt
        lengths = pos + 1                 # the row just written is live
        for i, bp in enumerate(blocks):
            h1 = _pp_ln(x, bp["ln1.weight"], bp["ln1.bias"], eps)
            qkv = _qmm(bp, "attn.qkv.weight", h1) + bp["attn.qkv.bias"]
            q, k_new, v_new = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, nh, D)
            k_new = k_new.reshape(B, nh, D)
            v_new = v_new.reshape(B, nh, D)
            k_pool = _kv_pool_write(k_pool, i, page_idx, offset, k_new)
            v_pool = _kv_pool_write(v_pool, i, page_idx, offset, v_new)
            o = _paged_attend(
                q, _kv_pool_layer(k_pool, i), _kv_pool_layer(v_pool, i),
                tables, lengths).reshape(B, -1)
            x = x + _qmm(bp, "attn.proj.weight", o) + bp["attn.proj.bias"]
            x = _ffn(bp, x)
        xf = _pp_ln(x, head["ln_f.weight"], head["ln_f.bias"], eps)
        logits = xf @ embed["wte.weight"].T
        return logits, k_pool, v_pool

    prefill, _ = gpt_decode_fns(cfg, eps=eps)
    return prefill, paged_step


def gpt_paged_verify_fns(cfg: GPTConfig, eps: float = 1e-5,
                         page_tokens: int = 16):
    """Pure multi-token verify step over a PAGED KV cache — the target
    side of speculative decoding.

    paged_verify(params,
                 k_pool, v_pool [layers, P, page_tokens, heads, head_dim],
                 tables    [B, W]  int32 (unused entries -> null page 0),
                 toks      [B, K1] int32 (token at position cache_len+i),
                 cache_len [B]     int32)
        -> (logits [B, K1, V], k_pool, v_pool)

    Row i of `toks` is the token at absolute position `cache_len + i`;
    its K/V lands at page tables[b, pos//pt], row pos%pt — the exact
    addressing `paged_step` uses, via one [B, K1] advanced-index scatter
    per layer. `logits[b, i]` is the target's next-token distribution
    AFTER consuming toks[b, :i+1], so one call scores every drafted
    position at once. Attention gathers the block table like the XLA
    reference kernel and masks per query: position p attends keys
    0..p, which includes the rows this very call just wrote (drafted
    tokens see their drafted predecessors). Positions at or past
    max_seq_len redirect their writes to the null page, so padded
    verify rows near the sequence cap never clobber live data. The math
    (f32 scores, -1e30 mask, exact gelu) mirrors `gpt_decode_fns` so a
    verified-and-accepted token stream is argmax-identical to plain
    incremental decode.
    """
    if cfg.moe_experts > 0:
        raise NotImplementedError(
            "gpt_paged_verify_fns: MoE blocks have no KV-decode path yet")
    D = cfg.head_dim
    nh = cfg.heads
    pt = int(page_tokens)
    scale = 1.0 / math.sqrt(D)

    def _ffn(bp, x):
        h2 = _pp_ln(x, bp["ln2.weight"], bp["ln2.bias"], eps)
        m = jax.nn.gelu(_qmm(bp, "fc1.weight", h2) + bp["fc1.bias"],
                        approximate=False)
        return x + _qmm(bp, "fc2.weight", m) + bp["fc2.bias"]

    def paged_verify(params, k_pool, v_pool, tables, toks, cache_len):
        embed, blocks, head = split_decode_params(params, cfg)
        B, K1 = toks.shape
        W = tables.shape[1]
        pos = cache_len.astype(jnp.int32)[:, None] \
            + jnp.arange(K1, dtype=jnp.int32)[None]          # [B, K1]
        valid = pos < cfg.max_seq_len
        pos_c = jnp.minimum(pos, cfg.max_seq_len - 1)
        x = embed["wte.weight"][toks] + embed["wpe.weight"][pos_c]
        slot = jnp.minimum(pos_c // pt, W - 1)
        page_idx = jnp.take_along_axis(tables, slot, axis=1)  # [B, K1]
        page_idx = jnp.where(valid, page_idx, 0)  # overruns -> null page
        offset = pos_c % pt
        kcap = W * pt
        # Attention is split prefix/window so the pool gather hoists out
        # of the layer loop: the committed prefix (rows < cache_len) is
        # gathered ONCE for all layers, while the K1 in-flight tokens
        # attend each other directly from this dispatch's fresh K/V
        # under an in-window causal triangle. Score layout per query is
        # [prefix rows | window rows]; one softmax over the concat keeps
        # the math identical to the single-gather formulation.
        keys_all = _kv_pool_take(k_pool, tables, axis=1) \
            .reshape(len(blocks), B, kcap, nh, D)
        vals_all = _kv_pool_take(v_pool, tables, axis=1) \
            .reshape(len(blocks), B, kcap, nh, D)
        prefix_live = jnp.arange(kcap, dtype=jnp.int32)[None, :] \
            < cache_len.astype(jnp.int32)[:, None]            # [B, kcap]
        prefix_live = prefix_live[:, None, None, :]           # [B,1,1,kcap]
        win = jnp.arange(K1, dtype=jnp.int32)
        win_causal = (win[None, :] <= win[:, None])[None, None]  # [1,1,K1,K1]
        k_news, v_news = [], []
        for i, bp in enumerate(blocks):
            h1 = _pp_ln(x, bp["ln1.weight"], bp["ln1.bias"], eps)
            qkv = _qmm(bp, "attn.qkv.weight", h1) + bp["attn.qkv.bias"]
            q, k_new, v_new = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, K1, nh, D)
            k_new = k_new.reshape(B, K1, nh, D)
            v_new = v_new.reshape(B, K1, nh, D)
            k_news.append(k_new)
            v_news.append(v_new)
            sp = jnp.einsum("bqhd,bkhd->bhqk", q, keys_all[i]) * scale
            sp = jnp.where(prefix_live, sp.astype(jnp.float32), -1e30)
            sw = jnp.einsum("bqhd,bkhd->bhqk", q, k_new) * scale
            sw = jnp.where(win_causal, sw.astype(jnp.float32), -1e30)
            s = jnp.concatenate([sp, sw], axis=-1)
            p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
            o = jnp.einsum("bhqk,bkhd->bqhd", p[..., :kcap], vals_all[i]) \
                + jnp.einsum("bhqk,bkhd->bqhd", p[..., kcap:], v_new)
            o = o.reshape(B, K1, -1)
            x = x + _qmm(bp, "attn.proj.weight", o) + bp["attn.proj.bias"]
            x = _ffn(bp, x)
        # one all-layer scatter of the fresh K/V (page_idx/offset are
        # layer-invariant); accepted rows persist, rejected rows become
        # garbage above the rolled-back cache_len, overruns hit page 0
        k_pool = _kv_pool_write(k_pool, slice(None), page_idx, offset,
                                jnp.stack(k_news))
        v_pool = _kv_pool_write(v_pool, slice(None), page_idx, offset,
                                jnp.stack(v_news))
        xf = _pp_ln(x, head["ln_f.weight"], head["ln_f.bias"], eps)
        logits = xf @ embed["wte.weight"].T
        amax = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return logits, amax, k_pool, v_pool

    return paged_verify


def gpt_paged_prefill_fns(cfg: GPTConfig, eps: float = 1e-5,
                          page_tokens: int = 16):
    """Pure fused prefill-into-pages: one executable computes the
    prompt's K/V panel (the parallel `gpt_decode_fns` prefill) AND
    scatters its rows into pool pages — replacing the three-hop
    prefill -> host panel copy -> page-write admission path with a
    single dispatch.

    paged_prefill(params,
                  k_pool, v_pool [layers, P, page_tokens, heads, head_dim],
                  toks   [1, R] int32 (prompt padded to the rung),
                  tables [1, W] int32 (W = ceil(R / page_tokens)),
                  n      [1]    int32 (true prompt length)
        -> (logits [1, V], k_pool, v_pool)

    Row r lands at page tables[0, r//pt], offset r%pt; padding rows at
    or past `n` redirect to the null page, so a short prompt in a wide
    rung never dirties pages it does not own. `logits` is the prefill's
    last-position head — callers that only want the K/V ignore it.
    """
    pt = int(page_tokens)
    prefill, _ = gpt_decode_fns(cfg, eps=eps)

    def paged_prefill(params, k_pool, v_pool, toks, tables, n):
        R = toks.shape[1]
        W = tables.shape[1]
        logits, k, v = prefill(params, toks, n)
        rows = jnp.arange(R, dtype=jnp.int32)
        valid = rows < n[0]
        slot = jnp.minimum(rows // pt, W - 1)
        page_idx = jnp.where(valid, tables[0, slot], 0)
        offset = rows % pt
        k_pool = _kv_pool_write(k_pool, slice(None), page_idx, offset,
                                k[:, 0])
        v_pool = _kv_pool_write(v_pool, slice(None), page_idx, offset,
                                v[:, 0])
        return logits, k_pool, v_pool

    return paged_prefill


def gpt_paged_rollout_fns(cfg: GPTConfig, eps: float = 1e-5,
                          page_tokens: int = 16):
    """Pure K-step greedy draft rollout over a PAGED KV cache — the
    draft side of speculative decoding fused into ONE executable, so a
    scheduler tick costs two dispatches (rollout + verify) instead of
    k + 1.

    paged_rollout(params,
                  k_pool, v_pool [layers, P, page_tokens, heads, head_dim],
                  tables [B, W] int32 (unused entries -> null page 0),
                  forced [B, K] int32 (>= 0: the committed token to
                          consume at step i — catch-up; -1: chain the
                          previous step's own argmax),
                  cache_len [B] int32)
        -> (drafts [B, K] int32, k_pool, v_pool)

    Step i consumes one token at absolute position `cache_len + i`,
    writes its K/V at page tables[b, pos//pt] row pos%pt (the exact
    `paged_step` addressing) and records the greedy argmax in
    `drafts[b, i]`. `forced[:, 0]` must be >= 0 — the engine always has
    at least one committed token the draft has not consumed. Positions
    at or past max_seq_len redirect their writes to the null page, so a
    slot drafting into the sequence cap never clobbers live rows.
    Attention is the gathered-pool XLA path of `paged_verify` with a
    single query row; draft numerics only move the acceptance rate,
    never output correctness, so no Pallas kernel is spent here.
    """
    if cfg.moe_experts > 0:
        raise NotImplementedError(
            "gpt_paged_rollout_fns: MoE blocks have no KV-decode path yet")
    D = cfg.head_dim
    nh = cfg.heads
    pt = int(page_tokens)
    scale = 1.0 / math.sqrt(D)

    def _ffn(bp, x):
        h2 = _pp_ln(x, bp["ln2.weight"], bp["ln2.bias"], eps)
        m = jax.nn.gelu(_qmm(bp, "fc1.weight", h2) + bp["fc1.bias"],
                        approximate=False)
        return x + _qmm(bp, "fc2.weight", m) + bp["fc2.bias"]

    def paged_rollout(params, k_pool, v_pool, tables, forced, cache_len):
        embed, blocks, head = split_decode_params(params, cfg)
        B, K = forced.shape
        W = tables.shape[1]
        kcap = W * pt
        base = cache_len.astype(jnp.int32)

        def step(i, carry):
            prev, drafts, k_pool, v_pool = carry
            want = jax.lax.dynamic_slice_in_dim(forced, i, 1, axis=1)[:, 0]
            tok = jnp.where(want >= 0, want, prev)
            pos = base + i
            valid = pos < cfg.max_seq_len
            pos_c = jnp.minimum(pos, cfg.max_seq_len - 1)
            x = embed["wte.weight"][tok] + embed["wpe.weight"][pos_c]
            slot = jnp.minimum(pos_c // pt, W - 1)
            page_idx = jnp.take_along_axis(
                tables, slot[:, None], axis=1)[:, 0]
            page_idx = jnp.where(valid, page_idx, 0)
            offset = pos_c % pt
            live = jnp.arange(kcap, dtype=jnp.int32)[None, :] \
                < (pos_c + 1)[:, None]                       # [B, kcap]
            for li, bp in enumerate(blocks):
                h1 = _pp_ln(x, bp["ln1.weight"], bp["ln1.bias"], eps)
                qkv = _qmm(bp, "attn.qkv.weight", h1) + bp["attn.qkv.bias"]
                q, k_new, v_new = jnp.split(qkv, 3, axis=-1)
                q = q.reshape(B, nh, D)
                k_new = k_new.reshape(B, nh, D)
                v_new = v_new.reshape(B, nh, D)
                k_pool = _kv_pool_write(k_pool, li, page_idx, offset, k_new)
                v_pool = _kv_pool_write(v_pool, li, page_idx, offset, v_new)
                keys = _kv_pool_take(_kv_pool_layer(k_pool, li),
                                     tables, axis=0) \
                    .reshape(B, kcap, nh, D)
                vals = _kv_pool_take(_kv_pool_layer(v_pool, li),
                                     tables, axis=0) \
                    .reshape(B, kcap, nh, D)
                s = jnp.einsum("bhd,bkhd->bhk", q, keys) * scale
                s = s.astype(jnp.float32)
                s = jnp.where(live[:, None], s, -1e30)
                p = jax.nn.softmax(s, axis=-1).astype(vals.dtype)
                o = jnp.einsum("bhk,bkhd->bhd", p, vals).reshape(B, -1)
                x = x + _qmm(bp, "attn.proj.weight", o) + bp["attn.proj.bias"]
                x = _ffn(bp, x)
            xf = _pp_ln(x, head["ln_f.weight"], head["ln_f.bias"], eps)
            logits = xf @ embed["wte.weight"].T
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            drafts = jax.lax.dynamic_update_slice_in_dim(
                drafts, nxt[:, None], i, axis=1)
            return nxt, drafts, k_pool, v_pool

        prev0 = forced[:, 0]
        drafts0 = jnp.zeros((B, K), jnp.int32)
        _, drafts, k_pool, v_pool = jax.lax.fori_loop(
            0, K, step, (prev0, drafts0, k_pool, v_pool))
        return drafts, k_pool, v_pool

    return paged_rollout
