"""Model zoo — transformer LM families matching the reference's headline
benchmark configs (BASELINE.md: ERNIE/BERT-base pretrain, GPT-2 345M,
GPT-3 1.3B). Vision models live in paddle_tpu.vision.models."""
from .gpt import (GPT, GPTConfig, gpt2_124m, gpt2_345m, gpt3_1p3b, gpt_tiny,
                  gpt_param_shardings)
from .bert import (Bert, BertConfig, bert_base, bert_tiny,
                   Ernie, ernie_base)

__all__ = ["GPT", "GPTConfig", "gpt2_124m", "gpt2_345m", "gpt3_1p3b",
           "gpt_tiny", "gpt_param_shardings",
           "Bert", "BertConfig", "bert_base", "bert_tiny",
           "Ernie", "ernie_base"]
