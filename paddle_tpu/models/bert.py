"""BERT/ERNIE-class bidirectional encoder (BASELINE config 3).

Built on the same nn stack as the reference's transformer layers
(/root/reference/python/paddle/nn/layer/transformer.py:437
TransformerEncoderLayer); provides the MLM pretraining head the ERNIE-base
benchmark exercises.
"""
from __future__ import annotations

import dataclasses

from .. import nn
from ..nn import functional as F


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30592          # 30522 padded to multiple of 128
    max_seq_len: int = 512
    type_vocab_size: int = 2
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    ffn_mult: int = 4
    dropout: float = 0.0
    fused_head_ce: bool = None   # see GPTConfig.fused_head_ce
    # scan-over-layers (see GPTConfig.scan_layers): stack encoder-layer
    # params on a leading [layers] axis and run them as one lax.scan step
    # so XLA compile time stays (near-)invariant in depth. Default on.
    scan_layers: bool = True


def bert_base(**kw):
    return BertConfig(**kw)


def bert_tiny(**kw):
    return BertConfig(vocab_size=512, max_seq_len=128, hidden=64, layers=2,
                      heads=4, **kw)


class Bert(nn.Layer):
    """Encoder + tied-embedding MLM head.
    forward(ids [B,T]) -> mlm logits [B,T,V]."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        from ..framework import ParamAttr
        from ..nn import initializer as I
        emb_init = ParamAttr(initializer=I.Normal(0.0, 0.02))
        self.tok = nn.Embedding(cfg.vocab_size, cfg.hidden, weight_attr=emb_init)
        self.pos = nn.Embedding(cfg.max_seq_len, cfg.hidden, weight_attr=emb_init)
        self.seg = nn.Embedding(cfg.type_vocab_size, cfg.hidden,
                                weight_attr=emb_init)
        self.ln = nn.LayerNorm(cfg.hidden)
        self.drop = nn.Dropout(cfg.dropout)
        enc_layer = nn.TransformerEncoderLayer(
            d_model=cfg.hidden, nhead=cfg.heads,
            dim_feedforward=cfg.ffn_mult * cfg.hidden,
            dropout=cfg.dropout, activation="gelu", normalize_before=True)
        self.encoder = nn.TransformerEncoder(enc_layer,
                                             num_layers=cfg.layers,
                                             scan_layers=cfg.scan_layers)
        self.mlm_ln = nn.LayerNorm(cfg.hidden)
        self.mlm_fc = nn.Linear(cfg.hidden, cfg.hidden)

    def forward(self, ids, token_type_ids=None, attn_mask=None):
        h = self.forward_hidden(ids, token_type_ids=token_type_ids,
                                attn_mask=attn_mask)
        return F.linear(h, self.tok.weight.transpose([1, 0]))

    def forward_hidden(self, ids, token_type_ids=None, attn_mask=None):
        """Post-MLM-transform hidden states [B,T,C] — the tied-head CE
        input (same split as GPT.forward_hidden)."""
        B, T = ids.shape
        from ..ops.creation import arange, zeros
        pos = arange(T, dtype="int64").unsqueeze(0)
        seg = (token_type_ids if token_type_ids is not None
               else zeros([B, T], dtype="int64"))
        x = self.tok(ids) + self.pos(pos) + self.seg(seg)
        x = self.drop(self.ln(x))
        x = self.encoder(x, src_mask=attn_mask)
        return self.mlm_ln(F.gelu(self.mlm_fc(x)))

    def mlm_loss(self, ids, labels, ignore_index=-100, **kw):
        """Tied-head MLM CE through the shared masked_linear_ce (the
        fused-CE path, ops/pallas/fused_ce.py): the [B*T, V] logits are
        recomputed in the VJP instead of being saved as residuals — on
        the ERNIE geometry (B=32, T=512, V=18048) the eliminated f32
        logits residual is ~1.2 GB/step of HBM traffic (the r4 config-3
        gap; VERDICT r4 Weak #1)."""
        from .gpt import masked_linear_ce
        h = self.forward_hidden(ids, **kw)
        return masked_linear_ce(h, self.tok.weight, labels,
                                ignore_index=ignore_index,
                                fused=getattr(self.cfg, "fused_head_ce",
                                              None))

    def num_params(self) -> int:
        import math
        return sum(int(math.prod(p.shape)) for p in self.parameters())

    def flops_per_token(self, seq_len=None) -> int:
        """Train-step (fwd+bwd) FLOPs/token — 6N + the attention
        score/value matmuls (same estimator as GPT.flops_per_token;
        bidirectional attention runs the full T×T score block)."""
        n = self.num_params()
        c = self.cfg
        attn = 12 * c.layers * c.hidden * (seq_len or c.max_seq_len)
        return 6 * n + attn

    def param_shardings(self, params, mesh_axis_tp="tp"):
        """Strategy-compiler protocol: Megatron TP PartitionSpecs.
        Column-parallel q/k/v + ffn-in, row-parallel out_proj + ffn-out,
        vocab-parallel token embedding; everything else replicated."""
        return bert_param_shardings(params, mesh_axis_tp=mesh_axis_tp)


def bert_param_shardings(params, mesh_axis_tp="tp"):
    from jax.sharding import PartitionSpec as P
    col_w = ("q_proj.weight", "k_proj.weight", "v_proj.weight",
             "linear1.weight")
    col_b = ("q_proj.bias", "k_proj.bias", "v_proj.bias", "linear1.bias")
    row_w = ("out_proj.weight", "linear2.weight")
    import re
    specs = {}
    for name, v in params.items():
        ndim = len(v.shape)
        # scan layout: "encoder.layers.{rel}" (no layer index) carries a
        # leading [layers] scan axis — replicate it, shard per-block dims
        stacked = ("encoder.layers." in name
                   and not re.search(r"encoder\.layers\.\d+\.", name))
        if stacked:
            ndim -= 1
        if any(name.endswith(s) for s in col_w):
            spec = P(None, mesh_axis_tp)
        elif any(name.endswith(s) for s in col_b):
            spec = P(mesh_axis_tp)
        elif any(name.endswith(s) for s in row_w):
            spec = P(mesh_axis_tp, None)
        elif name.endswith("tok.weight"):
            spec = P(mesh_axis_tp, None)
        elif ndim >= 2:
            spec = P(*([None] * ndim))
        else:
            spec = P()
        specs[name] = P(None, *spec) if stacked else spec
    return specs


def ernie_base(**kw):
    """ERNIE-base geometry (BASELINE config 3 names ERNIE explicitly).
    Architecturally the BERT encoder with ERNIE 1.0's zh vocab size; the
    ERNIE difference is the pretraining task (entity/phrase masking —
    a data-pipeline concern), not the network."""
    kw.setdefault("vocab_size", 18048)   # 18000 padded to multiple of 128
    return BertConfig(**kw)


Ernie = Bert     # reference ships ERNIE as a model zoo entry over BERT
