"""tpulint core — shared infrastructure for paddle_tpu's static-analysis pass.

This module carries everything the individual checkers share:

* :class:`Finding` — one diagnostic, identified by a stable ``TPLxxx`` rule id.
* :class:`SourceFile` — a parsed source file: text, AST (with parent links),
  and the inline ``# tpulint: disable=...`` suppression map.
* :class:`Baseline` — grandfathered findings loaded from a JSON file so a
  checker can be introduced without blocking CI on pre-existing debt.
* :class:`AnalysisContext` — the unit handed to every checker: the file set
  plus root-relative access to docs/catalog files for drift checks.

Checkers are plain modules exposing ``RULES`` (dict of rule id -> one-line
description) and ``check(ctx) -> list[Finding]``.  They must be pure: no
imports of the code under analysis, no side effects — everything is derived
from source text and ASTs so the linter can run on a broken tree.
"""

from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# Rule id owned by the core loader: files that fail to parse.
PARSE_RULE = "TPL001"

# Rule id owned by the baseline loader: grandfathered entries whose
# justification was never filled in after --write-baseline.
PLACEHOLDER_RULE = "TPL002"

# What write_baseline stamps into fresh entries; TPL002 fires while the
# literal text survives, so grandfathering stays a deliberate, explained
# act instead of a silent debt sink.
PLACEHOLDER_JUSTIFICATION = "TODO: explain why this finding is grandfathered"

CORE_RULES = {
    PARSE_RULE: "source file failed to parse (checkers skipped for the file)",
    PLACEHOLDER_RULE: (
        "baseline entry still carries the write-baseline placeholder "
        "justification"
    ),
}

_SUPPRESS_RE = re.compile(r"tpulint:\s*disable=([A-Za-z0-9_,\s]+)")


@dataclass
class Finding:
    """One diagnostic emitted by a checker."""

    rule: str
    path: str  # root-relative, posix separators
    line: int
    col: int
    symbol: str  # enclosing function/class, or "" at module level
    message: str

    def fingerprint(self) -> Tuple[str, str, str, str]:
        """Line-independent identity used for baseline matching."""
        return (self.rule, self.path, self.symbol, self.message)

    def format(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{loc}: {self.rule} {self.message}{sym}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "symbol": self.symbol,
            "message": self.message,
        }


def _collect_suppressions(text: str, lines: Sequence[str]) -> Dict[int, Set[str]]:
    """Map line number -> set of suppressed rule ids (or {"all"}).

    A ``# tpulint: disable=TPL011[,TPL021]`` comment applies to its own line
    when it trails code, or to the next code line when it stands alone.
    """
    out: Dict[int, Set[str]] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(text).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        if not rules:
            continue
        lineno = tok.start[0]
        before = lines[lineno - 1][: tok.start[1]] if lineno - 1 < len(lines) else ""
        targets = {lineno}
        if not before.strip():
            # Stand-alone comment: also applies to the next code line.
            for idx in range(lineno, len(lines)):
                stripped = lines[idx].strip()
                if not stripped or stripped.startswith("#"):
                    continue
                targets.add(idx + 1)
                break
        for t in targets:
            out.setdefault(t, set()).update(rules)
    return out


class SourceFile:
    """A parsed python source file with parent-linked AST and suppressions."""

    def __init__(self, abspath: Path, rel: str, text: str):
        self.abspath = abspath
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text)  # caller handles SyntaxError
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._tpl_parent = node  # type: ignore[attr-defined]
        self.suppressions = _collect_suppressions(text, self.lines)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return getattr(node, "_tpl_parent", None)

    def enclosing_symbol(self, node: ast.AST) -> str:
        """Dotted name of the enclosing class/function scope, or ""."""
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parent(cur)
        return ".".join(reversed(parts))

    def is_suppressed(self, line: int, rule: str) -> bool:
        rules = self.suppressions.get(line)
        if not rules:
            return False
        return "all" in rules or rule in rules


def file_suppressions(path: Path) -> Dict[int, Set[str]]:
    """Suppression map for a file on disk (empty when unreadable).

    Used by the runtime (--runtime) filter, where findings point at files
    that were never loaded as :class:`SourceFile` objects.
    """
    try:
        text = path.read_text()
    except (OSError, UnicodeDecodeError):
        return {}
    return _collect_suppressions(text, text.splitlines())


class Baseline:
    """Grandfathered findings: matched line-independently by fingerprint."""

    def __init__(self, entries: Iterable[dict]):
        self.entries = list(entries)
        self._keys = {
            (e.get("rule", ""), e.get("path", ""), e.get("symbol", ""), e.get("message", ""))
            for e in self.entries
        }

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not path.is_file():
            return cls([])
        data = json.loads(path.read_text())
        return cls(data.get("entries", []))

    def matches(self, finding: Finding) -> bool:
        return finding.fingerprint() in self._keys

    def placeholder_findings(self, rel_path: str) -> List[Finding]:
        """TPL002 findings for entries whose justification is still the
        write-baseline placeholder.  These target the baseline file
        itself (``rel_path``) and are emitted AFTER baseline matching —
        a baseline can never grandfather its own missing justifications.
        """
        out: List[Finding] = []
        for e in self.entries:
            just = str(e.get("justification", "")).strip()
            if PLACEHOLDER_JUSTIFICATION not in just:
                continue
            where = f"{e.get('rule', '?')} at {e.get('path', '?')}"
            if e.get("symbol"):
                where += f" [{e['symbol']}]"
            out.append(
                Finding(
                    rule=PLACEHOLDER_RULE,
                    path=rel_path,
                    line=1,
                    col=0,
                    symbol=str(e.get("rule", "")),
                    message=f"grandfathered {where}: replace the "
                    f"placeholder justification with why this finding "
                    f"is acceptable",
                )
            )
        return out

    def __len__(self) -> int:
        return len(self.entries)


def write_baseline(path: Path, findings: Sequence[Finding]) -> None:
    entries = [
        {
            "rule": f.rule,
            "path": f.path,
            "symbol": f.symbol,
            "message": f.message,
            "justification": PLACEHOLDER_JUSTIFICATION,
        }
        for f in findings
        # TPL002 points at the baseline file, not at source; writing it
        # back would grandfather the act of not justifying grandfathers.
        if f.rule != PLACEHOLDER_RULE
    ]
    path.write_text(json.dumps({"version": 1, "entries": entries}, indent=2) + "\n")


class AnalysisContext:
    """What every checker sees: the parsed file set plus the repo root."""

    def __init__(self, root: Path, files: Sequence[SourceFile]):
        self.root = root
        self.files = list(files)
        self._doc_cache: Dict[str, Optional[str]] = {}

    def read_root_file(self, rel: str) -> Optional[str]:
        """Text of a root-relative file (e.g. docs/observability.md), or None."""
        if rel not in self._doc_cache:
            p = self.root / rel
            self._doc_cache[rel] = p.read_text() if p.is_file() else None
        return self._doc_cache[rel]

    def find_file(self, rel_suffix: str) -> Optional[SourceFile]:
        """First analyzed file whose relative path ends with ``rel_suffix``."""
        for f in self.files:
            if f.rel == rel_suffix or f.rel.endswith("/" + rel_suffix):
                return f
        return None


# --------------------------------------------------------------------------
# Source loading
# --------------------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build", "dist"}


def iter_py_files(paths: Sequence[Path]) -> List[Path]:
    out: List[Path] = []
    seen: Set[Path] = set()
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            cands = [p]
        elif p.is_dir():
            cands = sorted(
                f
                for f in p.rglob("*.py")
                if not any(part in _SKIP_DIRS or part.startswith(".") for part in f.parts)
            )
        else:
            cands = []
        for c in cands:
            rc = c.resolve()
            if rc not in seen:
                seen.add(rc)
                out.append(c)
    return out


def discover_root(paths: Sequence[Path]) -> Path:
    """Walk up from the first path to a directory that looks like the repo root."""
    start = paths[0].resolve() if paths else Path.cwd()
    if start.is_file():
        start = start.parent
    cur = start
    while True:
        if (cur / "docs").is_dir() and (
            (cur / ".git").exists() or (cur / "pyproject.toml").is_file() or (cur / "ROADMAP.md").is_file()
        ):
            return cur
        if cur.parent == cur:
            return start
        cur = cur.parent


def load_sources(paths: Sequence[Path], root: Path) -> Tuple[List[SourceFile], List[Finding]]:
    files: List[SourceFile] = []
    findings: List[Finding] = []
    for p in iter_py_files(paths):
        try:
            rel = p.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = p.as_posix()
        try:
            text = p.read_text()
        except (OSError, UnicodeDecodeError) as exc:
            findings.append(Finding(PARSE_RULE, rel, 1, 0, "", f"unreadable: {exc}"))
            continue
        try:
            files.append(SourceFile(p, rel, text))
        except SyntaxError as exc:
            findings.append(
                Finding(PARSE_RULE, rel, exc.lineno or 1, exc.offset or 0, "", f"syntax error: {exc.msg}")
            )
    return files, findings


# --------------------------------------------------------------------------
# Small AST helpers shared by checkers
# --------------------------------------------------------------------------


def qualname(node: ast.AST) -> Optional[str]:
    """Dotted name for a Name/Attribute chain, e.g. ``self._lock`` — else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = qualname(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def literal_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def call_kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def qual_tail(qual: Optional[str], n: int = 2) -> str:
    """Last ``n`` dotted components of a qualname ("jax.lax.scan" -> "lax.scan")."""
    if not qual:
        return ""
    return ".".join(qual.split(".")[-n:])
