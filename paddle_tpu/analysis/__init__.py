"""tpulint — paddle_tpu's framework-native static-analysis subsystem.

Five checkers grounded in this repo's real bug classes:

====== =====================================================================
TPL01x trace-safety: host-impure calls inside jit/scan/pjit-traced functions
TPL02x lock-discipline: blocking calls under held locks, lock-order inversion
TPL03x thread-lifecycle: daemon/join proof, stop wiring for loop threads
TPL04x env-flag registry: PADDLE_TPU_* reads resolve through core.flags
TPL05x catalog drift: metrics/chaos-sites/admin endpoints vs docs
====== =====================================================================

Run it: ``python -m paddle_tpu.analysis paddle_tpu/`` (exit 0 = clean).
See docs/static_analysis.md for the rule catalog and suppression syntax.
"""

from .cli import CHECKERS, Result, all_rules, main, run
from .core import AnalysisContext, Baseline, Finding, SourceFile

__all__ = [
    "AnalysisContext",
    "Baseline",
    "CHECKERS",
    "Finding",
    "Result",
    "SourceFile",
    "all_rules",
    "main",
    "run",
]
