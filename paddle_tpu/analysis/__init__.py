"""tpulint — paddle_tpu's framework-native analysis subsystem.

Five static checkers grounded in this repo's real bug classes, plus a
runtime concurrency sanitizer that covers what AST analysis cannot:

====== =====================================================================
TPL01x trace-safety: host-impure calls inside jit/scan/pjit-traced
       functions; donated (``donate_argnums``) buffers read after the call
TPL02x lock-discipline: blocking calls under held locks, lock-order inversion
TPL03x thread-lifecycle: daemon/join proof, stop wiring for loop threads
TPL04x env-flag registry: PADDLE_TPU_* reads resolve through core.flags
TPL05x catalog drift: metrics/chaos-sites/admin endpoints vs docs
TPR1xx tsan-lite (:mod:`.runtime`): *observed* lock-order inversions,
       blocking-under-lock wall-clock holds, thread/lock leaks — armed via
       ``PADDLE_TPU_TSAN``, gated through the runtime pytest plugin
====== =====================================================================

Run the static pass: ``python -m paddle_tpu.analysis paddle_tpu/`` (exit
0 = clean).  Replay a runtime report: ``python -m paddle_tpu.analysis
--runtime report.json``.  See docs/static_analysis.md for the rule
catalog, the suppression syntax, and the tsan-lite workflow.
"""

from .cli import CHECKERS, Result, all_rules, filter_runtime, main, run, run_runtime_report
from .core import AnalysisContext, Baseline, Finding, SourceFile

__all__ = [
    "AnalysisContext",
    "Baseline",
    "CHECKERS",
    "Finding",
    "Result",
    "SourceFile",
    "all_rules",
    "filter_runtime",
    "main",
    "run",
    "run_runtime_report",
]
