"""TPL05x — catalog drift: docs, registries and code must name the same things.

The repo keeps three human-facing catalogs: the metric-family tables in
``docs/observability.md``, the chaos-site table in ``docs/fault_tolerance.md``
and the admin-endpoint list.  Each started life as prose and drifted the
moment code moved.  This checker generalizes the old hand-rolled metric-name
lint in ``tests/test_observability.py`` into a static pass over the *source*:

* TPL051 — a metric family definition (``counter/gauge/histogram`` call with
  literal name+help) violates naming conventions: ``paddle_tpu_`` prefix,
  lowercase snake case, counters end ``_total``, non-empty help, valid
  label names.  :func:`lint_metric_family` is shared with the runtime test
  so there is exactly one implementation of the rules.
* TPL052 — a metric family defined in code is absent from
  ``docs/observability.md`` (the doc tables use unprefixed names, so a
  suffix match counts).
* TPL053 — chaos-site drift between ``maybe_fail("site")`` call sites,
  the ``testing/chaos.py`` ``register_site`` registry, and the site table
  in ``docs/fault_tolerance.md``.
* TPL054 — an admin endpoint routed in ``observability/admin.py``
  (``path == "/x"``) that ``docs/observability.md`` never mentions.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import AnalysisContext, Finding, SourceFile, call_kwarg, literal_str, qual_tail, qualname

RULES = {
    "TPL051": "metric family violates naming/metadata conventions",
    "TPL052": "metric family defined in code but missing from docs/observability.md",
    "TPL053": "chaos-site drift between code, registry and docs/fault_tolerance.md",
    "TPL054": "admin endpoint routed in code but missing from docs/observability.md",
}

OBSERVABILITY_DOC = "docs/observability.md"
FAULT_DOC = "docs/fault_tolerance.md"
CHAOS_MODULE_SUFFIX = "testing/chaos.py"
ADMIN_MODULE_SUFFIX = "observability/admin.py"

METRIC_PREFIX = "paddle_tpu_"
_METRIC_NAME_RE = re.compile(r"^paddle_tpu_[a-z][a-z0-9_]*$")
_LABEL_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*$")
_METRIC_CTORS = {"counter", "gauge", "histogram"}


def lint_metric_family(kind: str, name: str, help_text: str, labelnames: Sequence[str]) -> List[str]:
    """Convention problems for one metric family; [] when clean.

    Shared between the static TPL051 pass and the runtime registry lint in
    tests/test_observability.py — one implementation of the rules.
    """
    problems: List[str] = []
    if not _METRIC_NAME_RE.match(name):
        problems.append(
            f"name '{name}' must match {_METRIC_NAME_RE.pattern} "
            "(paddle_tpu_ prefix, lowercase snake case)"
        )
    if kind == "counter" and not name.endswith("_total"):
        problems.append(f"counter '{name}' must end in '_total'")
    if not (help_text or "").strip():
        problems.append(f"metric '{name}' has empty help text")
    for label in labelnames:
        if label.startswith("__") or not _LABEL_NAME_RE.match(label):
            problems.append(f"metric '{name}' has invalid label name '{label}'")
    return problems


def _literal_seq(node: Optional[ast.AST]) -> Optional[List[str]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            s = literal_str(elt)
            if s is None:
                return None
            out.append(s)
        return out
    return None


def collect_metric_defs(sf: SourceFile) -> List[Tuple[ast.Call, str, str, str, List[str]]]:
    """(call, kind, name, help, labels) for literal metric definitions."""
    out = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        kind = qual_tail(qualname(node.func), 1)
        if kind not in _METRIC_CTORS or len(node.args) < 2:
            continue
        name = literal_str(node.args[0])
        help_text = literal_str(node.args[1])
        if name is None or help_text is None:
            continue
        labels_node = node.args[2] if len(node.args) > 2 else call_kwarg(node, "labelnames")
        labels = _literal_seq(labels_node) or []
        out.append((node, kind, name, help_text, labels))
    return out


def _doc_mentions_metric(doc: str, name: str) -> bool:
    if name in doc:
        return True
    return name.startswith(METRIC_PREFIX) and name[len(METRIC_PREFIX):] in doc


def _chaos_registered(ctx: AnalysisContext) -> Optional[Set[str]]:
    """Sites registered via register_site in testing/chaos.py, or None if absent."""
    sf = ctx.find_file(CHAOS_MODULE_SUFFIX)
    if sf is None:
        return None
    sites: Set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and qual_tail(qualname(node.func), 1) == "register_site":
            name = literal_str(node.args[0] if node.args else None)
            if name:
                sites.add(name)
    return sites


def _chaos_uses(ctx: AnalysisContext) -> Dict[str, Tuple[SourceFile, ast.Call]]:
    """site name -> first maybe_fail/fail_once call site."""
    uses: Dict[str, Tuple[SourceFile, ast.Call]] = {}
    for sf in ctx.files:
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Call)
                and qual_tail(qualname(node.func), 1) in ("maybe_fail", "fail_once")
            ):
                name = literal_str(node.args[0] if node.args else None)
                if name and name not in uses:
                    uses[name] = (sf, node)
    return uses


def _admin_endpoints(sf: SourceFile) -> List[Tuple[str, int]]:
    """Endpoint paths routed by literal comparison against the request path."""
    out: List[Tuple[str, int]] = []
    seen: Set[str] = set()
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Compare) and len(node.comparators) == 1):
            continue
        if not isinstance(node.ops[0], ast.Eq):
            continue
        for side in (node.left, node.comparators[0]):
            s = literal_str(side)
            if s and s.startswith("/") and s not in seen:
                seen.add(s)
                out.append((s, node.lineno))
    return out


def check(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    obs_doc = ctx.read_root_file(OBSERVABILITY_DOC)

    # --- TPL051 / TPL052: metric families -------------------------------
    documented_missing: Set[str] = set()
    for sf in ctx.files:
        for call, kind, name, help_text, labels in collect_metric_defs(sf):
            symbol = sf.enclosing_symbol(call)
            for problem in lint_metric_family(kind, name, help_text, labels):
                findings.append(
                    Finding("TPL051", sf.rel, call.lineno, call.col_offset, symbol, problem)
                )
            if obs_doc is not None and name not in documented_missing:
                if not _doc_mentions_metric(obs_doc, name):
                    documented_missing.add(name)
                    findings.append(
                        Finding(
                            "TPL052", sf.rel, call.lineno, call.col_offset, symbol,
                            f"metric family '{name}' is not documented in {OBSERVABILITY_DOC}",
                        )
                    )

    # --- TPL053: chaos sites --------------------------------------------
    registered = _chaos_registered(ctx)
    uses = _chaos_uses(ctx)
    if registered is not None:
        chaos_sf = ctx.find_file(CHAOS_MODULE_SUFFIX)
        chaos_rel = chaos_sf.rel if chaos_sf else CHAOS_MODULE_SUFFIX
        for name, (sf, node) in sorted(uses.items()):
            if name not in registered:
                findings.append(
                    Finding(
                        "TPL053", sf.rel, node.lineno, node.col_offset,
                        sf.enclosing_symbol(node),
                        f"chaos site '{name}' is injected here but not registered via "
                        "testing.chaos.register_site",
                    )
                )
        for name in sorted(registered - set(uses)):
            findings.append(
                Finding(
                    "TPL053", chaos_rel, 1, 0, "",
                    f"chaos site '{name}' is registered but no maybe_fail/fail_once "
                    "call site uses it — stale registration",
                )
            )
        fault_doc = ctx.read_root_file(FAULT_DOC)
        if fault_doc is None:
            if registered:
                findings.append(
                    Finding("TPL053", chaos_rel, 1, 0, "",
                            f"{FAULT_DOC} is missing but chaos sites are registered")
                )
        else:
            for name in sorted(registered):
                if name not in fault_doc:
                    findings.append(
                        Finding(
                            "TPL053", chaos_rel, 1, 0, "",
                            f"chaos site '{name}' is registered but not documented in {FAULT_DOC}",
                        )
                    )

    # --- TPL054: admin endpoints ----------------------------------------
    admin_sf = ctx.find_file(ADMIN_MODULE_SUFFIX)
    if admin_sf is not None and obs_doc is not None:
        for path, line in _admin_endpoints(admin_sf):
            if path not in obs_doc:
                findings.append(
                    Finding(
                        "TPL054", admin_sf.rel, line, 0, "",
                        f"admin endpoint '{path}' is routed in code but never mentioned "
                        f"in {OBSERVABILITY_DOC}",
                    )
                )
    return findings
