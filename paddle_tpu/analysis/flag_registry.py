"""TPL04x — env-flag registry: every PADDLE_TPU_* knob has one home.

Before this checker, ``PADDLE_TPU_*`` environment variables were read with
ad-hoc ``os.environ.get`` calls scattered across fourteen modules; nothing
listed them, nothing documented defaults, and a typo in a flag name failed
silently.  ``paddle_tpu/core/flags.py`` now carries a central env-flag
catalog (``define_env_flag`` / ``env_value`` / ``env_raw``); this checker
makes the catalog load-bearing:

* TPL041 — a direct ``os.environ[...]`` / ``os.environ.get`` / ``os.getenv``
  read of a ``PADDLE_TPU_*`` name outside the catalog module.  All reads
  must go through ``flags.env_value`` / ``flags.env_raw``.
* TPL042 — a ``PADDLE_TPU_*`` token (anywhere in source, comments included)
  that is not registered in the catalog: an undeclared knob.
* TPL043 — the catalog and ``docs/flags.md`` disagree (flag missing from
  the doc, or doc mentions a flag the catalog does not define).

The catalog is read *statically*: ``define_env_flag("NAME", ...)`` first-arg
literals are collected from the flags module's AST, so the linter never
imports the code it checks.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import AnalysisContext, Finding, SourceFile, literal_str, qual_tail, qualname

RULES = {
    "TPL041": "direct PADDLE_TPU_* environment read outside the flag catalog",
    "TPL042": "PADDLE_TPU_* name not registered in the env-flag catalog",
    "TPL043": "env-flag catalog out of sync with docs/flags.md",
}

FLAG_TOKEN_RE = re.compile(r"PADDLE_TPU_[A-Z0-9][A-Z0-9_]*")
FLAGS_MODULE_SUFFIX = "core/flags.py"
FLAGS_DOC = "docs/flags.md"


def _find_flags_module(ctx: AnalysisContext) -> Optional[ast.Module]:
    sf = ctx.find_file(FLAGS_MODULE_SUFFIX)
    if sf is not None:
        return sf.tree
    text = ctx.read_root_file("paddle_tpu/" + FLAGS_MODULE_SUFFIX)
    if text is not None:
        try:
            return ast.parse(text)
        except SyntaxError:
            return None
    return None


def load_catalog(ctx: AnalysisContext) -> Set[str]:
    """PADDLE_TPU_* names registered via define_env_flag in core/flags.py."""
    tree = _find_flags_module(ctx)
    names: Set[str] = set()
    if tree is None:
        return names
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and qual_tail(qualname(node.func), 1) == "define_env_flag":
            name = literal_str(node.args[0] if node.args else None)
            if name:
                names.add(name)
    return names


def _is_flags_module(sf: SourceFile) -> bool:
    return sf.rel.endswith(FLAGS_MODULE_SUFFIX)


def _direct_env_reads(sf: SourceFile) -> List[Tuple[ast.AST, str]]:
    """(node, flag-name) for os.environ/os.getenv reads of PADDLE_TPU_* names."""
    def _is_environ(q: Optional[str]) -> bool:
        # Matches os.environ and aliased imports (_os.environ, bare environ).
        return bool(q) and q.split(".")[-1] == "environ"

    out: List[Tuple[ast.AST, str]] = []
    for node in ast.walk(sf.tree):
        name: Optional[str] = None
        if isinstance(node, ast.Subscript) and _is_environ(qualname(node.value)):
            name = literal_str(node.slice)
        elif isinstance(node, ast.Call):
            qual = qualname(node.func) or ""
            parts = qual.split(".")
            if (parts[-1] == "get" and _is_environ(".".join(parts[:-1]))) or parts[-1] == "getenv":
                name = literal_str(node.args[0] if node.args else None)
        elif isinstance(node, ast.Compare) and len(node.comparators) == 1:
            # "PADDLE_TPU_<NAME>" in os.environ
            if (
                isinstance(node.ops[0], (ast.In, ast.NotIn))
                and _is_environ(qualname(node.comparators[0]))
            ):
                name = literal_str(node.left)
        if name and name.startswith("PADDLE_TPU_"):
            out.append((node, name))
    return out


def _token_lines(text: str) -> List[Tuple[str, int]]:
    """(token, 1-based line) for every PADDLE_TPU_* occurrence in raw text."""
    out: List[Tuple[str, int]] = []
    for i, line in enumerate(text.splitlines(), start=1):
        for m in FLAG_TOKEN_RE.finditer(line):
            out.append((m.group(0), i))
    return out


def check(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    catalog = load_catalog(ctx)

    for sf in ctx.files:
        if not _is_flags_module(sf):
            for node, name in _direct_env_reads(sf):
                findings.append(
                    Finding(
                        "TPL041",
                        sf.rel,
                        node.lineno,
                        node.col_offset,
                        sf.enclosing_symbol(node),
                        f"direct environment read of '{name}' — resolve it through "
                        "core.flags.env_value/env_raw so the catalog stays authoritative",
                    )
                )
        if catalog:
            seen: Set[str] = set()
            for token, line in _token_lines(sf.text):
                if token in catalog or token in seen:
                    continue
                seen.add(token)
                findings.append(
                    Finding(
                        "TPL042",
                        sf.rel,
                        line,
                        0,
                        "",
                        f"'{token}' is not registered in the env-flag catalog "
                        "(core/flags.py define_env_flag)",
                    )
                )

    if catalog:
        flags_file = ctx.find_file(FLAGS_MODULE_SUFFIX)
        doc_path = flags_file.rel if flags_file is not None else FLAGS_MODULE_SUFFIX
        doc = ctx.read_root_file(FLAGS_DOC)
        if doc is None:
            findings.append(
                Finding(
                    "TPL043", doc_path, 1, 0, "",
                    f"{FLAGS_DOC} is missing — regenerate it with "
                    "`python -m paddle_tpu.core.flags > docs/flags.md`",
                )
            )
        else:
            doc_tokens = {t for t, _ in _token_lines(doc)}
            for name in sorted(catalog - doc_tokens):
                findings.append(
                    Finding(
                        "TPL043", doc_path, 1, 0, "",
                        f"flag '{name}' is in the catalog but missing from {FLAGS_DOC} — "
                        "regenerate the doc",
                    )
                )
            for name in sorted(doc_tokens - catalog):
                findings.append(
                    Finding(
                        "TPL043", doc_path, 1, 0, "",
                        f"{FLAGS_DOC} documents '{name}' which the catalog does not define — "
                        "stale doc or missing define_env_flag",
                    )
                )
    return findings
