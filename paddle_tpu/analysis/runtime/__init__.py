"""tsan-lite: runtime concurrency sanitizer (tpulint's dynamic twin).

The package behind the ``TPR1xx`` rules in ``python -m paddle_tpu.analysis
--list-rules``:

* :mod:`.sanitizer` — the instrumented ``threading`` shims, armed via
  ``PADDLE_TPU_TSAN`` (lock-order graph / TPR101, blocking-under-lock /
  TPR102, leak audit / TPR103, ``paddle_tpu_tsan_*`` metric families).
* :mod:`.pytest_plugin` — ``pytest -p paddle_tpu.analysis.runtime.
  pytest_plugin``: arms the sanitizer for a test run, writes the JSON
  findings report (``PADDLE_TPU_TSAN_REPORT``) and fails the run on
  unsuppressed findings — the runtime CI gate next to the static one.

Replay a written report through suppression/baseline filtering with
``python -m paddle_tpu.analysis --runtime <report.json>``.
"""

from .sanitizer import (  # noqa: F401
    RULES,
    audit,
    default_root,
    enabled,
    findings,
    install,
    install_if_enabled,
    installed,
    report_data,
    reset,
    uninstall,
)

__all__ = [
    "RULES",
    "audit",
    "default_root",
    "enabled",
    "findings",
    "install",
    "install_if_enabled",
    "installed",
    "report_data",
    "reset",
    "uninstall",
]
