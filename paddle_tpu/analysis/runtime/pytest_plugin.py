"""pytest plugin arming the tsan-lite sanitizer — the runtime CI gate.

Usage (the designated concurrency modules; see ROADMAP.md tier-1 notes)::

    PADDLE_TPU_TSAN=1 python -m pytest -q \\
        tests/test_serve_batching.py tests/test_serve_chaos.py \\
        tests/test_decode.py tests/test_slo.py \\
        -p paddle_tpu.analysis.runtime.pytest_plugin

* ``pytest_configure`` arms the sanitizer (before test modules construct
  their locks/threads) — only when ``PADDLE_TPU_TSAN`` is set; with the
  flag off the plugin is inert and nothing is patched.
* ``pytest_sessionfinish`` runs the TPR103 leak audit, writes the raw JSON
  report to ``PADDLE_TPU_TSAN_REPORT`` (when set), filters findings
  through tpulint's suppression comments + baseline, prints what survives
  and fails the run (exit 1) on unsuppressed findings.

A written report replays offline with
``python -m paddle_tpu.analysis --runtime <report.json>``.
"""

from __future__ import annotations

import json
from pathlib import Path

from . import sanitizer

_ARMED = False


def pytest_configure(config):
    global _ARMED
    if sanitizer.install_if_enabled(root=_rootdir(config)) is not None:
        _ARMED = True


def _rootdir(config) -> Path:
    root = getattr(config, "rootpath", None)
    return Path(str(root)) if root is not None else sanitizer.default_root()


def pytest_sessionfinish(session, exitstatus):
    global _ARMED
    if not _ARMED:
        return
    _ARMED = False
    sanitizer.audit()
    raw = sanitizer.report_data()
    sanitizer.uninstall()

    from ...core import flags as _flags
    from ..cli import filter_runtime

    report_path = str(_flags.env_value("PADDLE_TPU_TSAN_REPORT") or "").strip()
    if report_path:
        Path(report_path).write_text(json.dumps(raw, indent=2) + "\n")

    root = _rootdir(session.config)
    result = filter_runtime(sanitizer.findings(), root)
    tw = print  # plain stdout: survives -q and capture teardown
    tw("")
    if result.findings:
        tw(f"tsan-lite: {len(result.findings)} unsuppressed runtime finding(s) "
           f"({result.suppressed} suppressed, {result.baselined} baselined):")
        for f in result.findings:
            tw(f"  {f.format()}")
        if report_path:
            tw(f"tsan-lite: report written to {report_path} "
               f"(replay: python -m paddle_tpu.analysis --runtime {report_path})")
        session.exitstatus = 1
    else:
        tw(f"tsan-lite: clean ({result.suppressed} suppressed, "
           f"{result.baselined} baselined)")
