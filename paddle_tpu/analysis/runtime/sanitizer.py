"""tsan-lite — the runtime concurrency sanitizer behind tpulint's TPR1xx rules.

tpulint's lock rules (TPL021/TPL022) are static and intra-module: a helper
that blocks while its caller holds a lock across a module boundary, or a
lock-order inversion between two *classes*, is invisible to the AST pass.
This module is the dynamic twin.  When armed (`install()`, normally via the
pytest plugin under ``PADDLE_TPU_TSAN=1``), ``threading.Lock`` / ``RLock`` /
``Condition`` / ``Thread`` are replaced with instrumented shims that maintain

* a process-global lock-order graph keyed per lock instance, with the
  acquisition stack recorded on every edge — any cycle is a lock-order
  inversion across whatever modules/classes the locks live in (**TPR101**,
  the dynamic superset of TPL022);
* wall-clock hold timing per lock: a hold segment crossing
  ``PADDLE_TPU_TSAN_BLOCK_MS`` means *something* blocked while holding the
  lock, wherever the blocking call lives (**TPR102**, the dynamic superset
  of TPL021).  ``Condition.wait`` on the held lock releases it and suspends
  the segment — the same designed-use exemption the static rule grants;
* hold/wait/contention ``paddle_tpu_tsan_*`` metric families registered
  through the observability registry (created only on install);
* an end-of-process audit (**TPR103**): non-daemon threads that were never
  joined and are still alive, and locks still held by threads that already
  exited.

Findings reuse tpulint's :class:`~paddle_tpu.analysis.core.Finding`
dataclass, so the line-oriented ``# tpulint: disable=TPR102`` suppression
comments and the JSON baseline work exactly as they do for static findings
(note: TPR101/TPR102 messages embed observed stacks/durations, so prefer
suppressions over baseline entries for runtime rules).  With
``PADDLE_TPU_TSAN`` off nothing is imported beyond this module and nothing
is patched — the idle path is byte-for-byte the stock ``threading`` module.
"""

from __future__ import annotations

import sys
import threading
import time
import weakref
from _thread import allocate_lock as _raw_lock
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from ..core import Finding

RULES = {
    "TPR101": "runtime lock-order inversion (cycle in the observed acquisition graph)",
    "TPR102": "lock hold segment crossed the blocking threshold (blocking work under a lock)",
    "TPR103": "end-of-process leak: non-daemon unjoined thread or never-released lock",
}

# The pristine primitives, captured at import — before install() can run.
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition
_REAL_THREAD = threading.Thread

_THIS_FILE = __file__
_THREADING_FILE = threading.__file__

_tls = threading.local()

#: current _State when installed, else None (module global so the shim
#: classes can reach it without holding per-instance references alive).
_STATE: Optional["_State"] = None


def _held_stack() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _thread_info() -> Tuple[int, str]:
    """(ident, name) for the current thread without threading.current_thread().

    current_thread() constructs a _DummyThread for unregistered threads —
    which happens mid-bootstrap (Thread._started.set() runs before the
    thread enters threading._active), and the construction would go through
    the patched Thread class.  A plain dict read avoids all of that.
    """
    ident = threading.get_ident()
    t = getattr(threading, "_active", {}).get(ident)
    return ident, (t.name if t is not None else f"thread-{ident}")


def _app_stack(skip: int = 2, limit: int = 8) -> List[Tuple[str, int, str]]:
    """(filename, lineno, funcname) frames, innermost first, skipping the
    sanitizer's own frames and threading.py internals.  No linecache I/O —
    this runs on every tracked acquire."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return []
    out: List[Tuple[str, int, str]] = []
    while f is not None and len(out) < limit:
        code = f.f_code
        fname = code.co_filename
        if fname != _THIS_FILE and fname != _THREADING_FILE:
            out.append((fname, f.f_lineno, code.co_name))
        f = f.f_back
    return out


class _Acq:
    """One held-lock record on a thread's held stack."""

    __slots__ = ("lock", "t0", "stack")

    def __init__(self, lock, t0, stack):
        self.lock = lock
        self.t0 = t0
        self.stack = stack


class _Edge:
    """One observed lock-order edge a->b with the stacks that created it."""

    __slots__ = ("thread", "stack_from", "stack_to")

    def __init__(self, thread, stack_from, stack_to):
        self.thread = thread
        self.stack_from = stack_from
        self.stack_to = stack_to


class _ThreadRecord:
    __slots__ = ("ref", "stack", "joined")

    def __init__(self, thread, stack):
        self.ref = weakref.ref(thread)
        self.stack = stack
        self.joined = False


class _State:
    """Everything one armed sanitizer session accumulates."""

    def __init__(self, block_threshold_s: float, root: Path):
        self.mu = _raw_lock()  # raw: never itself instrumented
        self.block_threshold_s = block_threshold_s
        self.root = root
        self.active = True
        self.next_uid = 1
        self.edges: Dict[int, Dict[int, _Edge]] = {}
        self.lock_labels: Dict[int, str] = {}  # uid -> creation-site label
        self.findings: List[Finding] = []
        self.finding_keys: set = set()
        self.locks: "weakref.WeakSet" = weakref.WeakSet()
        self.threads: List[_ThreadRecord] = []
        # metric instruments, bound by install()
        self.hold_hist = None
        self.wait_hist = None
        self.contention_ctr = None
        self.findings_ctr = None

    # -- identity ---------------------------------------------------------

    def new_uid(self, label: str) -> int:
        with self.mu:
            uid = self.next_uid
            self.next_uid += 1
            self.lock_labels[uid] = label
        return uid

    def rel(self, filename: str) -> str:
        try:
            return Path(filename).resolve().relative_to(self.root).as_posix()
        except (ValueError, OSError):
            return Path(filename).as_posix()

    def fmt_stack(self, stack, depth: int = 4) -> str:
        frames = [f"{self.rel(fn)}:{ln} in {name}" for fn, ln, name in stack[:depth]]
        return " <- ".join(frames) if frames else "<no app frames>"

    # -- findings ---------------------------------------------------------

    def emit(self, rule: str, dedup_key, stack, message: str) -> None:
        fn, line, sym = stack[0] if stack else ("<unknown>", 0, "")
        with self.mu:
            if dedup_key in self.finding_keys:
                return
            self.finding_keys.add(dedup_key)
            self.findings.append(
                Finding(rule, self.rel(fn), line, 0, sym, message)
            )
        if self.findings_ctr is not None:
            self.findings_ctr.labels(rule=rule).inc()

    # -- lock-order graph -------------------------------------------------

    def record_edges(self, held: List[_Acq], new_lock, new_stack) -> None:
        """Add held->new edges; report a TPR101 on any resulting cycle."""
        new_uid = new_lock._tsan_uid
        tname = _thread_info()[1]
        for acq in held:
            h_uid = acq.lock._tsan_uid
            if h_uid == new_uid:
                continue
            with self.mu:
                bucket = self.edges.setdefault(h_uid, {})
                fresh = new_uid not in bucket
                if fresh:
                    bucket[new_uid] = _Edge(tname, acq.stack, new_stack)
                path = self._find_path(new_uid, h_uid) if fresh else None
            if path:
                self._report_cycle(acq, new_lock, new_stack, tname, path)

    def _find_path(self, start: int, goal: int) -> Optional[List[int]]:
        """BFS over edges from start to goal (callers hold self.mu)."""
        if start not in self.edges:
            return None
        prev = {start: None}
        queue = [start]
        while queue:
            cur = queue.pop(0)
            for nxt in self.edges.get(cur, ()):
                if nxt in prev:
                    continue
                prev[nxt] = cur
                if nxt == goal:
                    out = [nxt]
                    while prev[out[-1]] is not None:
                        out.append(prev[out[-1]])
                    out.reverse()
                    return out
                queue.append(nxt)
        return None

    def _report_cycle(self, acq, new_lock, new_stack, tname, path) -> None:
        """path = [new_uid, ..., held_uid]: the opposite-order chain."""
        with self.mu:
            other = self.edges.get(path[0], {}).get(path[1])
            label_new = self.lock_labels.get(path[0], "?")
            label_held = self.lock_labels.get(path[-1], "?")
            chain = " -> ".join(self.lock_labels.get(u, "?") for u in path)
        if other is None:  # edge vanished (shouldn't happen); skip
            return
        dedup = ("TPR101", frozenset((label_new, label_held)))
        message = (
            f"lock-order inversion: thread '{tname}' acquires {label_held} "
            f"then {label_new} [held stack: {self.fmt_stack(acq.stack)}] "
            f"[acquire stack: {self.fmt_stack(new_stack)}], but thread "
            f"'{other.thread}' previously acquired {chain} "
            f"[their stacks: {self.fmt_stack(other.stack_from)} ; "
            f"{self.fmt_stack(other.stack_to)}]"
        )
        self.emit("TPR101", dedup, new_stack, message)

    # -- hold accounting --------------------------------------------------

    def end_segment(self, entry: _Acq, label: str) -> None:
        hold = time.monotonic() - entry.t0
        if self.hold_hist is not None:
            self.hold_hist.observe(hold)
        if hold >= self.block_threshold_s and entry.stack:
            fn, line, _sym = entry.stack[0]
            dedup = ("TPR102", fn, line)
            thr_ms = self.block_threshold_s * 1000.0
            self.emit(
                "TPR102", dedup, entry.stack,
                f"lock {label} held for {hold * 1000.0:.0f} ms "
                f"(threshold {thr_ms:g} ms) — blocking work under a lock "
                f"[acquired at: {self.fmt_stack(entry.stack)}]",
            )


# ---------------------------------------------------------------------------
# Lock shims
# ---------------------------------------------------------------------------


class TsanLock:
    """Instrumented stand-in for ``threading.Lock()``."""

    _inner_factory = staticmethod(_REAL_LOCK)
    _kind = "Lock"

    def __init__(self):
        st = _STATE
        self._inner = self._inner_factory()
        self._tsan_state = st
        self._holder = None  # (ident, thread name, t0, stack)
        if st is not None:
            stack = _app_stack()
            site = f"{st.rel(stack[0][0])}:{stack[0][1]}" if stack else "?"
            self._tsan_uid = st.new_uid(f"<{self._kind} {site}>")
            st.locks.add(self)
        else:
            self._tsan_uid = 0

    # -- helpers ----------------------------------------------------------

    def _label(self) -> str:
        st = self._tsan_state
        return st.lock_labels.get(self._tsan_uid, "?") if st else "?"

    def _tracking(self) -> bool:
        st = self._tsan_state
        return (
            st is not None and st.active and not getattr(_tls, "busy", False)
        )

    def _inner_acquire(self, blocking, timeout):
        if timeout is None or timeout < 0:
            return self._inner.acquire(blocking)
        return self._inner.acquire(blocking, timeout)

    # -- the Lock protocol -------------------------------------------------

    def acquire(self, blocking=True, timeout=-1):
        if not self._tracking():
            return self._inner_acquire(blocking, timeout)
        got = self._inner.acquire(False)
        waited, contended = 0.0, False
        if not got:
            if not blocking:
                return False
            contended = True
            t0 = time.monotonic()
            got = self._inner_acquire(True, timeout)
            waited = time.monotonic() - t0
        st = self._tsan_state
        _tls.busy = True
        try:
            if contended:
                if st.contention_ctr is not None:
                    st.contention_ctr.inc()
                if st.wait_hist is not None:
                    st.wait_hist.observe(waited)
            if got:
                self._on_acquired()
        finally:
            _tls.busy = False
        return got

    def _on_acquired(self):
        """Record stack/edges/holder; caller holds _tls.busy."""
        stack = _app_stack(skip=3)
        held = _held_stack()
        if held:
            self._tsan_state.record_edges(held, self, stack)
        now = time.monotonic()
        held.append(_Acq(self, now, stack))
        ident, name = _thread_info()
        self._holder = (ident, name, now, stack)

    def release(self):
        self._inner.release()
        if not self._tracking():
            self._holder = None
            return
        _tls.busy = True
        try:
            self._holder = None
            entry = self._pop_entry()
            if entry is not None:
                self._tsan_state.end_segment(entry, self._label())
        finally:
            _tls.busy = False

    def _pop_entry(self) -> Optional[_Acq]:
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is self:
                return held.pop(i)
        return None

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<TsanLock {self._label()} inner={self._inner!r}>"

    # -- Condition-compat hooks (if handed to a *real* Condition) ---------

    def _release_save(self):
        self.release()

    def _acquire_restore(self, _state):
        self.acquire()

    def _is_owned(self):
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    # -- Condition.wait bracketing ----------------------------------------

    def _suspend_for_wait(self):
        """Close the current hold segment around a Condition.wait; returns
        an opaque token for :meth:`_resume_after_wait` (None = untracked)."""
        if not self._tracking():
            return None
        _tls.busy = True
        try:
            entry = self._pop_entry()
            if entry is not None:
                self._tsan_state.end_segment(entry, self._label())
            holder, self._holder = self._holder, None
            return (entry, holder)
        finally:
            _tls.busy = False

    def _resume_after_wait(self, token):
        if token is None:
            return
        entry, _old_holder = token
        if entry is None:
            return
        if not (self._tsan_state is not None and self._tsan_state.active):
            return
        _tls.busy = True
        try:
            now = time.monotonic()
            stack = _app_stack(skip=3)
            _held_stack().append(_Acq(self, now, stack))
            ident, name = _thread_info()
            self._holder = (ident, name, now, stack)
        finally:
            _tls.busy = False


class TsanRLock(TsanLock):
    """Instrumented stand-in for ``threading.RLock()`` — the held stack
    carries one entry per lock regardless of recursion depth."""

    _inner_factory = staticmethod(_REAL_RLOCK)
    _kind = "RLock"

    def __init__(self):
        super().__init__()
        self._owner = None
        self._depth = 0

    def acquire(self, blocking=True, timeout=-1):
        ident = threading.get_ident()
        if self._owner == ident:  # recursive re-acquire: always succeeds
            self._inner_acquire(True, -1)
            self._depth += 1
            return True
        got = super().acquire(blocking, timeout)
        if got:
            self._owner = ident
            self._depth = 1
        return got

    def release(self):
        if self._owner != threading.get_ident():
            # let the inner RLock raise its own "not owned" error
            self._inner.release()
            return
        self._depth -= 1
        if self._depth > 0:
            self._inner.release()
            return
        self._owner = None
        super().release()

    # real-Condition compat: fully unwind the recursion like RLock does
    def _release_save(self):
        depth, owner = self._depth, self._owner
        self._depth = 1  # force the tracked release below
        self._owner = threading.get_ident()
        super().release()
        for _ in range(depth - 1):
            self._inner.release()
        return (depth, owner)

    def _acquire_restore(self, state):
        depth, _owner = state
        self.acquire()
        for _ in range(depth - 1):
            self.acquire()

    def _is_owned(self):
        return self._owner == threading.get_ident()

    def _suspend_for_wait(self):
        token = super()._suspend_for_wait()
        if token is None:
            return None
        depth, self._depth, self._owner = self._depth, 0, None
        return (token, depth)

    def _resume_after_wait(self, token):
        if token is None:
            return
        inner_token, depth = token
        super()._resume_after_wait(inner_token)
        self._owner = threading.get_ident()
        self._depth = depth


class TsanCondition:
    """Instrumented stand-in for ``threading.Condition``.

    Built over the *inner* raw lock of a Tsan lock so the stock Condition
    machinery does the real waiting, while acquire/release/wait go through
    the shim for hold tracking.  ``wait`` suspends the hold segment — time
    parked on the condition is the designed use, not blocking-under-lock.
    """

    def __init__(self, lock=None):
        if lock is None:
            lock = TsanRLock()
        self._tsan_lock = lock if isinstance(lock, TsanLock) else None
        inner = lock._inner if self._tsan_lock is not None else lock
        self._cond = _REAL_CONDITION(inner)

    # -- lock protocol, through the shim ----------------------------------

    def acquire(self, *args, **kwargs):
        if self._tsan_lock is not None:
            return self._tsan_lock.acquire(*args, **kwargs)
        return self._cond.acquire(*args, **kwargs)

    def release(self):
        if self._tsan_lock is not None:
            return self._tsan_lock.release()
        return self._cond.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # -- waiting -----------------------------------------------------------

    def wait(self, timeout=None):
        if self._tsan_lock is None:
            return self._cond.wait(timeout)
        token = self._tsan_lock._suspend_for_wait()
        try:
            return self._cond.wait(timeout)
        finally:
            self._tsan_lock._resume_after_wait(token)

    def wait_for(self, predicate, timeout=None):
        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = time.monotonic() + timeout
                waittime = endtime - time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait(None)
            result = predicate()
        return result

    def notify(self, n=1):
        return self._cond.notify(n)

    def notify_all(self):
        return self._cond.notify_all()

    notifyAll = notify_all

    def __repr__(self):
        return f"<TsanCondition over {self._tsan_lock!r}>"


class TsanThread(_REAL_THREAD):
    """Thread shim: records creation for the end-of-process leak audit."""

    def __init__(self, *args, **kwargs):
        if not isinstance(self, TsanThread):
            # threading internals (e.g. _DummyThread) call the module-global
            # Thread.__init__ unbound with a real-Thread subclass instance.
            _REAL_THREAD.__init__(self, *args, **kwargs)
            return
        super().__init__(*args, **kwargs)
        st = _STATE
        self._tsan_rec = None
        if st is not None and st.active:
            rec = _ThreadRecord(self, _app_stack())
            self._tsan_rec = rec
            with st.mu:
                st.threads.append(rec)

    def join(self, timeout=None):
        super().join(timeout)
        if self._tsan_rec is not None and not self.is_alive():
            self._tsan_rec.joined = True


# ---------------------------------------------------------------------------
# install / audit / report
# ---------------------------------------------------------------------------


def enabled() -> bool:
    """True when PADDLE_TPU_TSAN arms the sanitizer (flag-catalog parse)."""
    from ...core import flags as _flags

    return bool(_flags.env_value("PADDLE_TPU_TSAN"))


def installed() -> bool:
    return _STATE is not None and _STATE.active


def default_root() -> Path:
    """The repo root this installation of paddle_tpu lives in."""
    return Path(__file__).resolve().parents[3]


def install(root=None) -> "_State":
    """Arm the sanitizer: patch threading and register the metric families.

    Explicit call — flag gating belongs to the caller (the pytest plugin
    uses :func:`install_if_enabled`).  Idempotent while armed.
    """
    global _STATE
    if _STATE is not None and _STATE.active:
        return _STATE
    from ...core import flags as _flags
    from ...observability import metrics as _metrics

    thr_ms = float(_flags.env_value("PADDLE_TPU_TSAN_BLOCK_MS"))
    st = _State(thr_ms / 1000.0, Path(root) if root else default_root())
    st.hold_hist = _metrics.histogram(
        "paddle_tpu_tsan_lock_hold_seconds",
        "Wall-clock seconds each instrumented lock was held per hold "
        "segment (tsan-lite sanitizer; Condition.wait suspends the "
        "segment).")
    st.wait_hist = _metrics.histogram(
        "paddle_tpu_tsan_lock_wait_seconds",
        "Wall-clock seconds acquirers spent blocked on contended "
        "instrumented locks (tsan-lite sanitizer).")
    st.contention_ctr = _metrics.counter(
        "paddle_tpu_tsan_lock_contentions_total",
        "Lock acquisitions that found the lock already held "
        "(tsan-lite sanitizer).")
    st.findings_ctr = _metrics.counter(
        "paddle_tpu_tsan_findings_total",
        "Runtime concurrency-sanitizer findings emitted, by TPR1xx rule.",
        ("rule",))
    _STATE = st
    threading.Lock = TsanLock
    threading.RLock = TsanRLock
    threading.Condition = TsanCondition
    threading.Thread = TsanThread
    return st


def install_if_enabled(root=None) -> Optional["_State"]:
    """Plugin entry point: arm only when PADDLE_TPU_TSAN is set; with the
    flag off this touches nothing (zero shimming)."""
    if not enabled():
        return None
    return install(root)


def uninstall() -> None:
    """Restore the pristine threading primitives; state is kept readable
    (report()/findings()) until the next install()."""
    global _STATE
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    threading.Thread = _REAL_THREAD
    if _STATE is not None:
        _STATE.active = False


def audit() -> List[Finding]:
    """End-of-process leak audit (TPR103); returns the findings it added."""
    st = _STATE
    if st is None:
        return []
    before = len(st.findings)
    alive_idents = {t.ident for t in threading.enumerate()}
    with st.mu:
        threads = list(st.threads)
    for rec in threads:
        t = rec.ref()
        if t is None:
            continue  # collected => finished and reclaimed
        if t.is_alive() and not t.daemon and not rec.joined:
            st.emit(
                "TPR103", ("TPR103-thread", id(rec)), rec.stack,
                f"non-daemon thread '{t.name}' started here was never "
                "joined and is still alive at the end-of-process audit",
            )
    for lk in list(st.locks):
        holder = lk._holder
        if holder is None or not lk.locked():
            continue
        ident, tname, _t0, stack = holder
        if ident not in alive_idents:
            st.emit(
                "TPR103", ("TPR103-lock", lk._tsan_uid), stack,
                f"lock {lk._label()} is still held by thread '{tname}' "
                "which already exited — never released",
            )
    with st.mu:
        return list(st.findings[before:])


def findings() -> List[Finding]:
    st = _STATE
    if st is None:
        return []
    with st.mu:
        return list(st.findings)


def reset() -> None:
    """Drop accumulated findings/edges (between tests of the sanitizer)."""
    st = _STATE
    if st is None:
        return
    with st.mu:
        st.findings.clear()
        st.finding_keys.clear()
        st.edges.clear()
        st.threads.clear()


def report_data(root=None) -> dict:
    """Raw (unfiltered) report payload the pytest plugin writes to disk;
    replay through suppressions/baseline with
    ``python -m paddle_tpu.analysis --runtime <file>``."""
    st = _STATE
    if root is not None:
        root_s = str(root)
    else:
        root_s = str(st.root if st is not None else default_root())
    return {
        "version": 1,
        "kind": "tsan",
        "root": root_s,
        "rules": dict(RULES),
        "findings": [f.to_json() for f in findings()],
    }
