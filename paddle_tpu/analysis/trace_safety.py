"""TPL01x — trace-safety: host-impure work inside traced functions.

JAX traces a function once and replays the jaxpr; any host-side effect
(`time.time`, `random.random`, `os.environ`, materializing a tracer with
`float()`/`.item()`) executes at *trace* time, silently baking one value into
the compiled computation.  This is the static twin of the runtime retrace
guard: it finds functions handed to `jax.jit` / `pjit` / `lax.scan` /
`lax.while_loop` / `lax.cond` / `lax.fori_loop` (as decorators or call
arguments) and flags host-impure calls inside them, one helper level deep.

* TPL011 — direct host-impure call (`time.*`, `random.*`, `np.random.*`,
  `os.environ` / `os.getenv`) in a traced function.
* TPL012 — tracer materialization (`float()` / `int()` / `np.asarray()` /
  `.item()` / `.tolist()` on values derived from the traced function's
  parameters), or a host-impure call inside a same-module helper invoked
  from a traced function.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import AnalysisContext, Finding, SourceFile, call_kwarg, qual_tail, qualname

RULES = {
    "TPL011": "host-impure call inside a traced function",
    "TPL012": "tracer materialization or host-impure helper reachable from a traced function",
}

# Entry points whose function-valued arguments are traced.  Maps the
# 2-component qualname tail to the positional indices holding callees.
_TRACE_CALL_ARGS = {
    "jax.jit": (0,),
    "jax.pjit": (0,),
    "lax.scan": (0,),
    "lax.map": (0,),
    "lax.while_loop": (0, 1),
    "lax.cond": (1, 2),
    "lax.fori_loop": (2,),
    "jax.vmap": (0,),
    "jax.pmap": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
}
_TRACE_BARE = {"jit", "pjit"}  # bare decorator/call names that also count

# Call-name prefixes that are host-impure no matter what they touch.
_IMPURE_PREFIXES = (
    "time.",
    "random.",
    "np.random.",
    "numpy.random.",
    "os.environ",
    "os.getenv",
    "os.urandom",
)

# Materializers: pull a concrete value out of a tracer.
_MATERIALIZE_CALLS = {"float", "int", "bool"}
_MATERIALIZE_FUNCS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}
_MATERIALIZE_METHODS = {"item", "tolist"}


def _is_trace_entry(qual: Optional[str]) -> Optional[Tuple[int, ...]]:
    """Positional callee indices if ``qual`` names a tracing entry point."""
    if not qual:
        return None
    if qual in _TRACE_BARE or qual_tail(qual, 1) in _TRACE_BARE:
        return (0,)
    tail = qual_tail(qual, 2)
    if tail in _TRACE_CALL_ARGS:
        return _TRACE_CALL_ARGS[tail]
    return None


def _resolve_name(sf: SourceFile, node: ast.AST, name: str) -> Optional[ast.AST]:
    """Lexically resolve ``name`` to a def visible from ``node``.

    Walks enclosing function scopes outward to module level.  ClassDef
    scopes are skipped — python name resolution inside a method does not
    see class-level names.
    """
    cur: Optional[ast.AST] = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
            for child in cur.body:
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) and child.name == name:
                    return child
        cur = sf.parent(cur)
    return None


def _collect_traced(sf: SourceFile) -> List[Tuple[ast.AST, str]]:
    """All function nodes handed to a tracing entry point, with a label."""
    traced: List[Tuple[ast.AST, str]] = []
    seen: Set[int] = set()

    def add(fn: Optional[ast.AST], label: str) -> None:
        if fn is None or id(fn) in seen:
            return
        seen.add(id(fn))
        traced.append((fn, label))

    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                dq = qualname(dec)
                if _is_trace_entry(dq) is not None:
                    add(node, node.name)
                elif isinstance(dec, ast.Call):
                    cq = qualname(dec.func)
                    if _is_trace_entry(cq) is not None:
                        add(node, node.name)
                    elif qual_tail(cq, 1) == "partial" and dec.args:
                        if _is_trace_entry(qualname(dec.args[0])) is not None:
                            add(node, node.name)
        elif isinstance(node, ast.Call):
            idxs = _is_trace_entry(qualname(node.func))
            if idxs is None:
                continue
            for i in idxs:
                if i >= len(node.args):
                    continue
                arg = node.args[i]
                if isinstance(arg, ast.Lambda):
                    add(arg, "<lambda>")
                elif isinstance(arg, ast.Name):
                    add(_resolve_name(sf, node, arg.id), arg.id)
    return traced


def _impure_call(call: ast.Call) -> Optional[str]:
    qual = qualname(call.func)
    if not qual:
        return None
    for pre in _IMPURE_PREFIXES:
        if qual == pre.rstrip(".") or qual.startswith(pre):
            return qual
    return None


def _impure_subscript(node: ast.Subscript) -> Optional[str]:
    qual = qualname(node.value)
    if qual == "os.environ":
        return qual
    return None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _tainted_names(fn: ast.AST) -> Set[str]:
    """Parameter names plus names assigned from expressions touching them."""
    if isinstance(fn, ast.Lambda):
        args = fn.args
    else:
        args = fn.args  # type: ignore[union-attr]
    tainted: Set[str] = {a.arg for a in args.args + args.posonlyargs + args.kwonlyargs}
    if args.vararg:
        tainted.add(args.vararg.arg)
    if args.kwarg:
        tainted.add(args.kwarg.arg)
    if isinstance(fn, ast.Lambda):
        return tainted
    for _ in range(2):  # cheap fixpoint: two passes cover chained assigns
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _names_in(node.value) & tainted:
                for tgt in node.targets:
                    tainted |= _names_in(tgt)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) and node.value is not None:
                if _names_in(node.value) & tainted:
                    tainted |= _names_in(node.target)
    return tainted


def _walk_no_nested_defs(fn: ast.AST):
    """Walk a function body without descending into nested def/lambda bodies."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def check(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for sf in ctx.files:
        traced = _collect_traced(sf)
        traced_ids = {id(fn) for fn, _ in traced}
        emitted: Set[Tuple[str, int, str]] = set()

        def emit(rule: str, node: ast.AST, label: str, msg: str) -> None:
            key = (rule, node.lineno, msg)
            if key in emitted:
                return
            emitted.add(key)
            findings.append(Finding(rule, sf.rel, node.lineno, node.col_offset, label, msg))

        for fn, label in traced:
            tainted = _tainted_names(fn)
            for node in _walk_no_nested_defs(fn):
                if isinstance(node, ast.Call):
                    imp = _impure_call(node)
                    if imp:
                        emit("TPL011", node, label,
                             f"host-impure call '{imp}' inside traced function — "
                             "its value is frozen at trace time")
                        continue
                    fq = qualname(node.func)
                    # Materialization of traced values.
                    if fq in _MATERIALIZE_CALLS and node.args and _names_in(node.args[0]) & tainted:
                        emit("TPL012", node, label,
                             f"'{fq}()' materializes a traced value — forces host sync "
                             "and breaks under jit")
                    elif fq in _MATERIALIZE_FUNCS and node.args and _names_in(node.args[0]) & tainted:
                        emit("TPL012", node, label,
                             f"'{fq}()' materializes a traced value inside a traced function")
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr in _MATERIALIZE_METHODS
                          and _names_in(node.func.value) & tainted):
                        emit("TPL012", node, label,
                             f"'.{node.func.attr}()' on a traced value materializes it "
                             "inside a traced function")
                    # One level deep: helper lexically visible from the call.
                    elif isinstance(node.func, ast.Name):
                        helper = _resolve_name(sf, node, node.func.id)
                        if helper is None or id(helper) in traced_ids or helper is fn:
                            continue
                        for hnode in _walk_no_nested_defs(helper):
                            if isinstance(hnode, ast.Call):
                                himp = _impure_call(hnode)
                                if himp:
                                    emit("TPL012", hnode, node.func.id,
                                         f"host-impure call '{himp}' in helper "
                                         f"'{node.func.id}' reached from traced "
                                         f"function '{label}'")
                            elif isinstance(hnode, ast.Subscript) and _impure_subscript(hnode):
                                emit("TPL012", hnode, node.func.id,
                                     f"'os.environ[...]' read in helper '{node.func.id}' "
                                     f"reached from traced function '{label}'")
                elif isinstance(node, ast.Subscript) and _impure_subscript(node):
                    emit("TPL011", node, label,
                         "'os.environ[...]' read inside traced function — "
                         "its value is frozen at trace time")
    return findings
