"""TPL01x — trace-safety: host-impure work inside traced functions.

JAX traces a function once and replays the jaxpr; any host-side effect
(`time.time`, `random.random`, `os.environ`, materializing a tracer with
`float()`/`.item()`) executes at *trace* time, silently baking one value into
the compiled computation.  This is the static twin of the runtime retrace
guard: it finds functions handed to `jax.jit` / `pjit` / `lax.scan` /
`lax.while_loop` / `lax.cond` / `lax.fori_loop` (as decorators or call
arguments) and flags host-impure calls inside them, one helper level deep.

* TPL011 — direct host-impure call (`time.*`, `random.*`, `np.random.*`,
  `os.environ` / `os.getenv`) in a traced function.
* TPL012 — tracer materialization (`float()` / `int()` / `np.asarray()` /
  `.item()` / `.tolist()` on values derived from the traced function's
  parameters), or a host-impure call inside a same-module helper invoked
  from a traced function.
* TPL013 — donation safety: a value passed in a ``donate_argnums`` position
  of a jitted callable is read again after the call.  XLA is free to alias
  the donated buffer into the output, so the post-call read observes
  garbage (the async-pipeline / unaliased-put bug class).  Rebinding the
  name from the call's own result (``state = step(state, ...)``) is the
  sanctioned idiom and stays quiet.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import AnalysisContext, Finding, SourceFile, call_kwarg, qual_tail, qualname

RULES = {
    "TPL011": "host-impure call inside a traced function",
    "TPL012": "tracer materialization or host-impure helper reachable from a traced function",
    "TPL013": "donated argument read after the donating call (buffer may be aliased away)",
}

# Entry points whose function-valued arguments are traced.  Maps the
# 2-component qualname tail to the positional indices holding callees.
_TRACE_CALL_ARGS = {
    "jax.jit": (0,),
    "jax.pjit": (0,),
    "lax.scan": (0,),
    "lax.map": (0,),
    "lax.while_loop": (0, 1),
    "lax.cond": (1, 2),
    "lax.fori_loop": (2,),
    "jax.vmap": (0,),
    "jax.pmap": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
}
_TRACE_BARE = {"jit", "pjit"}  # bare decorator/call names that also count

# Call-name prefixes that are host-impure no matter what they touch.
_IMPURE_PREFIXES = (
    "time.",
    "random.",
    "np.random.",
    "numpy.random.",
    "os.environ",
    "os.getenv",
    "os.urandom",
)

# Materializers: pull a concrete value out of a tracer.
_MATERIALIZE_CALLS = {"float", "int", "bool"}
_MATERIALIZE_FUNCS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}
_MATERIALIZE_METHODS = {"item", "tolist"}


def _is_trace_entry(qual: Optional[str]) -> Optional[Tuple[int, ...]]:
    """Positional callee indices if ``qual`` names a tracing entry point."""
    if not qual:
        return None
    if qual in _TRACE_BARE or qual_tail(qual, 1) in _TRACE_BARE:
        return (0,)
    tail = qual_tail(qual, 2)
    if tail in _TRACE_CALL_ARGS:
        return _TRACE_CALL_ARGS[tail]
    return None


def _resolve_name(sf: SourceFile, node: ast.AST, name: str) -> Optional[ast.AST]:
    """Lexically resolve ``name`` to a def visible from ``node``.

    Walks enclosing function scopes outward to module level.  ClassDef
    scopes are skipped — python name resolution inside a method does not
    see class-level names.
    """
    cur: Optional[ast.AST] = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
            for child in cur.body:
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) and child.name == name:
                    return child
        cur = sf.parent(cur)
    return None


def _collect_traced(sf: SourceFile) -> List[Tuple[ast.AST, str]]:
    """All function nodes handed to a tracing entry point, with a label."""
    traced: List[Tuple[ast.AST, str]] = []
    seen: Set[int] = set()

    def add(fn: Optional[ast.AST], label: str) -> None:
        if fn is None or id(fn) in seen:
            return
        seen.add(id(fn))
        traced.append((fn, label))

    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                dq = qualname(dec)
                if _is_trace_entry(dq) is not None:
                    add(node, node.name)
                elif isinstance(dec, ast.Call):
                    cq = qualname(dec.func)
                    if _is_trace_entry(cq) is not None:
                        add(node, node.name)
                    elif qual_tail(cq, 1) == "partial" and dec.args:
                        if _is_trace_entry(qualname(dec.args[0])) is not None:
                            add(node, node.name)
        elif isinstance(node, ast.Call):
            idxs = _is_trace_entry(qualname(node.func))
            if idxs is None:
                continue
            for i in idxs:
                if i >= len(node.args):
                    continue
                arg = node.args[i]
                if isinstance(arg, ast.Lambda):
                    add(arg, "<lambda>")
                elif isinstance(arg, ast.Name):
                    add(_resolve_name(sf, node, arg.id), arg.id)
    return traced


def _impure_call(call: ast.Call) -> Optional[str]:
    qual = qualname(call.func)
    if not qual:
        return None
    for pre in _IMPURE_PREFIXES:
        if qual == pre.rstrip(".") or qual.startswith(pre):
            return qual
    return None


def _impure_subscript(node: ast.Subscript) -> Optional[str]:
    qual = qualname(node.value)
    if qual == "os.environ":
        return qual
    return None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _tainted_names(fn: ast.AST) -> Set[str]:
    """Parameter names plus names assigned from expressions touching them."""
    if isinstance(fn, ast.Lambda):
        args = fn.args
    else:
        args = fn.args  # type: ignore[union-attr]
    tainted: Set[str] = {a.arg for a in args.args + args.posonlyargs + args.kwonlyargs}
    if args.vararg:
        tainted.add(args.vararg.arg)
    if args.kwarg:
        tainted.add(args.kwarg.arg)
    if isinstance(fn, ast.Lambda):
        return tainted
    for _ in range(2):  # cheap fixpoint: two passes cover chained assigns
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _names_in(node.value) & tainted:
                for tgt in node.targets:
                    tainted |= _names_in(tgt)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) and node.value is not None:
                if _names_in(node.value) & tainted:
                    tainted |= _names_in(node.target)
    return tainted


def _walk_no_nested_defs(fn: ast.AST):
    """Walk a function body without descending into nested def/lambda bodies."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# TPL013 — donation safety
# ---------------------------------------------------------------------------

_DONATE_ENTRIES = {"jit", "pjit"}


def _donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Literal donate_argnums positions if ``call`` is jit/pjit with them."""
    qual = qualname(call.func)
    if not qual or qual_tail(qual, 1) not in _DONATE_ENTRIES:
        return None
    dn = call_kwarg(call, "donate_argnums")
    if isinstance(dn, ast.Constant) and type(dn.value) is int:
        return (dn.value,)
    if isinstance(dn, (ast.Tuple, ast.List)):
        out: List[int] = []
        for elt in dn.elts:
            if not (isinstance(elt, ast.Constant) and type(elt.value) is int):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def _collect_donors(sf: SourceFile) -> Dict[str, Tuple[int, ...]]:
    """Names bound to a donating jit: ``step = jax.jit(f, donate_argnums=..)``
    assignments plus ``@partial(jax.jit, donate_argnums=..)`` decorations."""
    donors: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            pos = _donate_positions(node.value)
            if pos:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        donors[tgt.id] = pos
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                pos = _donate_positions(dec)
                if pos is None and qual_tail(qualname(dec.func), 1) == "partial" and dec.args:
                    if qual_tail(qualname(dec.args[0]), 1) in _DONATE_ENTRIES:
                        dn = call_kwarg(dec, "donate_argnums")
                        fake = ast.Call(func=dec.args[0], args=[], keywords=dec.keywords)
                        pos = _donate_positions(fake) if dn is not None else None
                if pos:
                    donors[node.name] = pos
    return donors


def _enclosing_scope(sf: SourceFile, node: ast.AST) -> ast.AST:
    cur = sf.parent(node)
    while cur is not None and not isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
        cur = sf.parent(cur)
    return cur if cur is not None else sf.tree


def _stmt_rebinds(sf: SourceFile, call: ast.Call, name: str) -> bool:
    """True when the statement holding ``call`` assigns ``name`` from it
    (``state = step(state, ..)`` — the donated buffer is never read again)."""
    cur = sf.parent(call)
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = sf.parent(cur)
    if isinstance(cur, ast.Assign):
        return any(name in _names_in(t) for t in cur.targets)
    if isinstance(cur, (ast.AugAssign, ast.AnnAssign)):
        return name in _names_in(cur.target)
    return False


def _loop_ancestor(sf: SourceFile, call: ast.Call, scope: ast.AST) -> Optional[ast.AST]:
    cur = sf.parent(call)
    while cur is not None and cur is not scope:
        if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
            return cur
        cur = sf.parent(cur)
    return None


def _check_donation(sf: SourceFile, findings: List[Finding]) -> None:
    donors = _collect_donors(sf)
    if not donors:
        return
    emitted: Set[Tuple[int, str]] = set()

    def emit(node: ast.AST, msg: str) -> None:
        key = (node.lineno, msg)
        if key in emitted:
            return
        emitted.add(key)
        findings.append(
            Finding("TPL013", sf.rel, node.lineno, node.col_offset,
                    sf.enclosing_symbol(node), msg)
        )

    for call in ast.walk(sf.tree):
        if not (isinstance(call, ast.Call) and isinstance(call.func, ast.Name)):
            continue
        positions = donors.get(call.func.id)
        if not positions:
            continue
        scope = _enclosing_scope(sf, call)
        call_end = (call.end_lineno or call.lineno, call.end_col_offset or 0)
        for pos in positions:
            if pos >= len(call.args) or not isinstance(call.args[pos], ast.Name):
                continue
            donated = call.args[pos].id
            rebound_here = _stmt_rebinds(sf, call, donated)
            names = [
                n for n in _walk_no_nested_defs(scope)
                if isinstance(n, ast.Name) and n.id == donated
            ]
            stores_after = sorted(
                (n.lineno, n.col_offset) for n in names
                if isinstance(n.ctx, (ast.Store, ast.Del))
                and (n.lineno, n.col_offset) > call_end
            )
            if rebound_here:
                # ``x = step(x, ..)``: the rebind lands at the call itself.
                stores_after.insert(0, call_end)
            loads_after = sorted(
                ((n, (n.lineno, n.col_offset)) for n in names
                 if isinstance(n.ctx, ast.Load)
                 and (n.lineno, n.col_offset) > call_end),
                key=lambda item: item[1])
            if loads_after:
                node, where = loads_after[0]
                if not (stores_after and stores_after[0] <= where):
                    emit(node,
                         f"'{donated}' is donated to '{call.func.id}' "
                         f"(donate_argnums position {pos}) but read after the "
                         "call — the buffer may be aliased into the output; "
                         "copy it or rebind from the result")
                    continue
            loop = _loop_ancestor(sf, call, scope)
            if loop is not None:
                loop_stores = any(
                    isinstance(n, ast.Name) and n.id == donated
                    and isinstance(n.ctx, ast.Store)
                    for n in _walk_no_nested_defs(loop)
                )
                if not loop_stores:
                    emit(call,
                         f"'{donated}' is donated to '{call.func.id}' inside a "
                         "loop but never rebound there — the next iteration "
                         "reads the donated (possibly aliased-away) buffer")


def check(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for sf in ctx.files:
        _check_donation(sf, findings)
        traced = _collect_traced(sf)
        traced_ids = {id(fn) for fn, _ in traced}
        emitted: Set[Tuple[str, int, str]] = set()

        def emit(rule: str, node: ast.AST, label: str, msg: str) -> None:
            key = (rule, node.lineno, msg)
            if key in emitted:
                return
            emitted.add(key)
            findings.append(Finding(rule, sf.rel, node.lineno, node.col_offset, label, msg))

        for fn, label in traced:
            tainted = _tainted_names(fn)
            for node in _walk_no_nested_defs(fn):
                if isinstance(node, ast.Call):
                    imp = _impure_call(node)
                    if imp:
                        emit("TPL011", node, label,
                             f"host-impure call '{imp}' inside traced function — "
                             "its value is frozen at trace time")
                        continue
                    fq = qualname(node.func)
                    # Materialization of traced values.
                    if fq in _MATERIALIZE_CALLS and node.args and _names_in(node.args[0]) & tainted:
                        emit("TPL012", node, label,
                             f"'{fq}()' materializes a traced value — forces host sync "
                             "and breaks under jit")
                    elif fq in _MATERIALIZE_FUNCS and node.args and _names_in(node.args[0]) & tainted:
                        emit("TPL012", node, label,
                             f"'{fq}()' materializes a traced value inside a traced function")
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr in _MATERIALIZE_METHODS
                          and _names_in(node.func.value) & tainted):
                        emit("TPL012", node, label,
                             f"'.{node.func.attr}()' on a traced value materializes it "
                             "inside a traced function")
                    # One level deep: helper lexically visible from the call.
                    elif isinstance(node.func, ast.Name):
                        helper = _resolve_name(sf, node, node.func.id)
                        if helper is None or id(helper) in traced_ids or helper is fn:
                            continue
                        for hnode in _walk_no_nested_defs(helper):
                            if isinstance(hnode, ast.Call):
                                himp = _impure_call(hnode)
                                if himp:
                                    emit("TPL012", hnode, node.func.id,
                                         f"host-impure call '{himp}' in helper "
                                         f"'{node.func.id}' reached from traced "
                                         f"function '{label}'")
                            elif isinstance(hnode, ast.Subscript) and _impure_subscript(hnode):
                                emit("TPL012", hnode, node.func.id,
                                     f"'os.environ[...]' read in helper '{node.func.id}' "
                                     f"reached from traced function '{label}'")
                elif isinstance(node, ast.Subscript) and _impure_subscript(node):
                    emit("TPL011", node, label,
                         "'os.environ[...]' read inside traced function — "
                         "its value is frozen at trace time")
    return findings
