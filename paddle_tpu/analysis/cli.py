"""tpulint CLI — run the paddle_tpu static-analysis pass.

Usage::

    python -m paddle_tpu.analysis [paths...] [--json] [--rules TPL02,TPL041]
                                  [--baseline FILE] [--write-baseline]
                                  [--root DIR] [--list-rules]
    python -m paddle_tpu.analysis --runtime report.json [--json] [--rules ...]

The second form replays a tsan-lite runtime report (written by the
``paddle_tpu.analysis.runtime.pytest_plugin`` pytest plugin under
``PADDLE_TPU_TSAN=1``) through the same suppression-comment and baseline
filtering the static findings get — one workflow for both passes.

Exit codes: 0 = clean, 1 = unsuppressed findings, 2 = usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from . import catalog_drift, flag_registry, lock_discipline, thread_lifecycle, trace_safety
from .core import (
    CORE_RULES,
    AnalysisContext,
    Baseline,
    Finding,
    discover_root,
    file_suppressions,
    load_sources,
    write_baseline,
)
from .runtime.sanitizer import RULES as RUNTIME_RULES

CHECKERS = [trace_safety, lock_discipline, thread_lifecycle, flag_registry, catalog_drift]

DEFAULT_BASELINE = ".tpulint-baseline.json"
JSON_VERSION = 1


def all_rules() -> Dict[str, str]:
    rules = dict(CORE_RULES)
    for mod in CHECKERS:
        rules.update(mod.RULES)
    rules.update(RUNTIME_RULES)
    return dict(sorted(rules.items()))


@dataclass
class Result:
    """Outcome of one analysis run (also the JSON payload shape)."""

    root: str
    findings: List[Finding] = field(default_factory=list)  # active (reported)
    suppressed: int = 0
    baselined: int = 0

    @property
    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        return {
            "version": JSON_VERSION,
            "root": self.root,
            "findings": [f.to_json() for f in self.findings],
            "counts": self.counts,
            "suppressed": self.suppressed,
            "baselined": self.baselined,
        }


def run(
    paths: Sequence[str],
    root: Optional[str] = None,
    rules: Optional[Sequence[str]] = None,
    baseline_path: Optional[str] = None,
) -> Result:
    """Run every checker over ``paths``; returns active findings only.

    ``rules`` filters by prefix ("TPL02" keeps the whole lock family).
    ``baseline_path`` defaults to <root>/.tpulint-baseline.json when present.
    """
    path_objs = [Path(p) for p in paths]
    root_path = Path(root).resolve() if root else discover_root(path_objs)
    files, findings = load_sources(path_objs, root_path)
    ctx = AnalysisContext(root_path, files)
    for mod in CHECKERS:
        findings.extend(mod.check(ctx))

    prefixes = tuple(r.strip() for r in rules if r.strip()) if rules else ()
    if prefixes:
        findings = [f for f in findings if f.rule.startswith(prefixes)]
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))

    by_rel = {sf.rel: sf for sf in files}
    bl_path = Path(baseline_path) if baseline_path else root_path / DEFAULT_BASELINE
    baseline = Baseline.load(bl_path)

    result = Result(root=str(root_path))
    for f in findings:
        sf = by_rel.get(f.path)
        if sf is not None and sf.is_suppressed(f.line, f.rule):
            result.suppressed += 1
        elif baseline.matches(f):
            result.baselined += 1
        else:
            result.findings.append(f)

    # TPL002: unjustified grandfathers, reported against the baseline
    # file itself and exempt from baseline matching by construction.
    try:
        bl_rel = bl_path.resolve().relative_to(root_path).as_posix()
    except ValueError:
        bl_rel = bl_path.as_posix()
    stale = baseline.placeholder_findings(bl_rel)
    if prefixes:
        stale = [f for f in stale if f.rule.startswith(prefixes)]
    result.findings.extend(stale)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return result


def filter_runtime(
    findings: Sequence[Finding],
    root: Path,
    rules: Optional[Sequence[str]] = None,
    baseline_path: Optional[str] = None,
) -> Result:
    """Runtime findings through the static pass's suppression/baseline model.

    Suppression comments are read from the file each finding points at
    (``# tpulint: disable=TPR102`` on the acquire line works exactly like a
    static suppression); the baseline matches by the same line-independent
    fingerprint.  Shared by ``--runtime`` and the pytest plugin.
    """
    active = list(findings)
    if rules:
        prefixes = tuple(r.strip() for r in rules if r.strip())
        active = [f for f in active if f.rule.startswith(prefixes)]
    active.sort(key=lambda f: (f.path, f.line, f.rule, f.message))

    bl_path = Path(baseline_path) if baseline_path else root / DEFAULT_BASELINE
    baseline = Baseline.load(bl_path)
    supp_cache: Dict[str, Dict[int, set]] = {}

    result = Result(root=str(root))
    for f in active:
        supp = supp_cache.get(f.path)
        if supp is None:
            p = Path(f.path)
            supp = file_suppressions(p if p.is_absolute() else root / f.path)
            supp_cache[f.path] = supp
        rules_at = supp.get(f.line, set())
        if "all" in rules_at or f.rule in rules_at:
            result.suppressed += 1
        elif baseline.matches(f):
            result.baselined += 1
        else:
            result.findings.append(f)
    return result


def run_runtime_report(
    report_path: str,
    root: Optional[str] = None,
    rules: Optional[Sequence[str]] = None,
    baseline_path: Optional[str] = None,
) -> Result:
    """Load a tsan-lite JSON report and filter it (the --runtime mode)."""
    data = json.loads(Path(report_path).read_text())
    findings = [
        Finding(
            rule=str(e.get("rule", "")),
            path=str(e.get("path", "")),
            line=int(e.get("line", 0)),
            col=int(e.get("col", 0)),
            symbol=str(e.get("symbol", "")),
            message=str(e.get("message", "")),
        )
        for e in data.get("findings", [])
    ]
    root_path = Path(root).resolve() if root else Path(data.get("root") or ".").resolve()
    return filter_runtime(findings, root_path, rules=rules, baseline_path=baseline_path)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="tpulint: static analysis for the paddle_tpu codebase",
    )
    parser.add_argument("paths", nargs="*", default=["paddle_tpu"],
                        help="files or directories to analyze (default: paddle_tpu)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit machine-readable JSON instead of text")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule-id prefixes to keep (e.g. TPL02,TPL041)")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: <root>/{DEFAULT_BASELINE} if present)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write current findings to the baseline file and exit 0")
    parser.add_argument("--root", default=None,
                        help="repo root for docs/catalog lookups (default: auto-discovered)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--runtime", default=None, metavar="REPORT",
                        help="replay a tsan-lite runtime report (JSON written by the "
                             "paddle_tpu.analysis.runtime pytest plugin) through "
                             "suppression/baseline filtering instead of running the "
                             "static checkers")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, desc in all_rules().items():
            print(f"{rule}  {desc}")
        return 0

    rules = args.rules.split(",") if args.rules else None

    if args.runtime is not None:
        if not Path(args.runtime).is_file():
            print(f"error: no such report: {args.runtime}", file=sys.stderr)
            return 2
        try:
            result = run_runtime_report(
                args.runtime, root=args.root, rules=rules, baseline_path=args.baseline)
        except (ValueError, KeyError) as exc:
            print(f"error: malformed runtime report: {exc}", file=sys.stderr)
            return 2
    else:
        for p in args.paths:
            if not Path(p).exists():
                print(f"error: no such path: {p}", file=sys.stderr)
                return 2
        result = run(args.paths, root=args.root, rules=rules, baseline_path=args.baseline)

    if args.write_baseline:
        bl = Path(args.baseline) if args.baseline else Path(result.root) / DEFAULT_BASELINE
        write_baseline(bl, result.findings)
        print(f"wrote {len(result.findings)} finding(s) to {bl}")
        return 0

    if args.as_json:
        print(json.dumps(result.to_json(), indent=2))
    else:
        for f in result.findings:
            print(f.format())
        tail = (
            f"{len(result.findings)} finding(s), {result.suppressed} suppressed, "
            f"{result.baselined} baselined"
        )
        print(tail if result.findings else f"tpulint: clean ({tail})")
    return 1 if result.findings else 0
