"""tpulint CLI — run the paddle_tpu static-analysis pass.

Usage::

    python -m paddle_tpu.analysis [paths...] [--json] [--rules TPL02,TPL041]
                                  [--baseline FILE] [--write-baseline]
                                  [--root DIR] [--list-rules]

Exit codes: 0 = clean, 1 = unsuppressed findings, 2 = usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from . import catalog_drift, flag_registry, lock_discipline, thread_lifecycle, trace_safety
from .core import (
    CORE_RULES,
    AnalysisContext,
    Baseline,
    Finding,
    discover_root,
    load_sources,
    write_baseline,
)

CHECKERS = [trace_safety, lock_discipline, thread_lifecycle, flag_registry, catalog_drift]

DEFAULT_BASELINE = ".tpulint-baseline.json"
JSON_VERSION = 1


def all_rules() -> Dict[str, str]:
    rules = dict(CORE_RULES)
    for mod in CHECKERS:
        rules.update(mod.RULES)
    return dict(sorted(rules.items()))


@dataclass
class Result:
    """Outcome of one analysis run (also the JSON payload shape)."""

    root: str
    findings: List[Finding] = field(default_factory=list)  # active (reported)
    suppressed: int = 0
    baselined: int = 0

    @property
    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        return {
            "version": JSON_VERSION,
            "root": self.root,
            "findings": [f.to_json() for f in self.findings],
            "counts": self.counts,
            "suppressed": self.suppressed,
            "baselined": self.baselined,
        }


def run(
    paths: Sequence[str],
    root: Optional[str] = None,
    rules: Optional[Sequence[str]] = None,
    baseline_path: Optional[str] = None,
) -> Result:
    """Run every checker over ``paths``; returns active findings only.

    ``rules`` filters by prefix ("TPL02" keeps the whole lock family).
    ``baseline_path`` defaults to <root>/.tpulint-baseline.json when present.
    """
    path_objs = [Path(p) for p in paths]
    root_path = Path(root).resolve() if root else discover_root(path_objs)
    files, findings = load_sources(path_objs, root_path)
    ctx = AnalysisContext(root_path, files)
    for mod in CHECKERS:
        findings.extend(mod.check(ctx))

    if rules:
        prefixes = tuple(r.strip() for r in rules if r.strip())
        findings = [f for f in findings if f.rule.startswith(prefixes)]
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))

    by_rel = {sf.rel: sf for sf in files}
    bl_path = Path(baseline_path) if baseline_path else root_path / DEFAULT_BASELINE
    baseline = Baseline.load(bl_path)

    result = Result(root=str(root_path))
    for f in findings:
        sf = by_rel.get(f.path)
        if sf is not None and sf.is_suppressed(f.line, f.rule):
            result.suppressed += 1
        elif baseline.matches(f):
            result.baselined += 1
        else:
            result.findings.append(f)
    return result


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="tpulint: static analysis for the paddle_tpu codebase",
    )
    parser.add_argument("paths", nargs="*", default=["paddle_tpu"],
                        help="files or directories to analyze (default: paddle_tpu)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit machine-readable JSON instead of text")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule-id prefixes to keep (e.g. TPL02,TPL041)")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: <root>/{DEFAULT_BASELINE} if present)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write current findings to the baseline file and exit 0")
    parser.add_argument("--root", default=None,
                        help="repo root for docs/catalog lookups (default: auto-discovered)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, desc in all_rules().items():
            print(f"{rule}  {desc}")
        return 0

    for p in args.paths:
        if not Path(p).exists():
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2

    rules = args.rules.split(",") if args.rules else None
    result = run(args.paths, root=args.root, rules=rules, baseline_path=args.baseline)

    if args.write_baseline:
        bl = Path(args.baseline) if args.baseline else Path(result.root) / DEFAULT_BASELINE
        write_baseline(bl, result.findings)
        print(f"wrote {len(result.findings)} finding(s) to {bl}")
        return 0

    if args.as_json:
        print(json.dumps(result.to_json(), indent=2))
    else:
        for f in result.findings:
            print(f.format())
        tail = (
            f"{len(result.findings)} finding(s), {result.suppressed} suppressed, "
            f"{result.baselined} baselined"
        )
        print(tail if result.findings else f"tpulint: clean ({tail})")
    return 1 if result.findings else 0
