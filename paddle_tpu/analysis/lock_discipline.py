"""TPL02x — lock-discipline: blocking work under locks, lock-order inversions.

The serving stack holds dozens of ``with self._lock:`` sites across batching,
router, decode and metrics.  Two bug classes recur in concurrent systems like
this one:

* TPL021 — a blocking call made while a lock is held (socket I/O,
  ``subprocess``, ``time.sleep``, XLA ``.compile()`` / ``block_until_ready``,
  unbounded ``queue.get`` / ``Thread.join`` / ``Event.wait``).  Every other
  thread touching that lock stalls behind the slow operation.
* TPL022 — two methods of one class acquire the same pair of locks in
  opposite orders: a classic deadlock waiting for the right interleaving.

The checker builds a per-class map of lock-typed attributes (anything
assigned ``threading.Lock/RLock/Condition`` in any method), then walks each
method tracking the stack of held locks through ``with`` blocks.
``Condition.wait``/``wait_for`` on the *held* condition is exempt — that is
the designed use.  ``re.compile`` is exempt from the compile rule.
Analysis is intra-method: a helper that blocks while its caller holds a lock
is out of scope (documented limitation).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import AnalysisContext, Finding, SourceFile, call_kwarg, qual_tail, qualname

RULES = {
    "TPL021": "blocking call while holding a lock",
    "TPL022": "lock-order inversion between methods of a class",
}

_LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "lock",
    "threading.Condition": "condition",
    "Lock": "lock",
    "RLock": "lock",
    "Condition": "condition",
}
_TYPED_CTORS = {
    "threading.Event": "event",
    "Event": "event",
    "threading.Thread": "thread",
    "Thread": "thread",
    "queue.Queue": "queue",
    "Queue": "queue",
    "queue.SimpleQueue": "queue",
    "SimpleQueue": "queue",
}
_TYPED_CTORS.update(_LOCK_CTORS)

_SOCKET_BLOCKING_METHODS = {"recv", "recv_into", "sendall", "accept"}


def _attr_types(cls: ast.ClassDef) -> Dict[str, str]:
    """self.X -> type tag ("lock"/"condition"/"event"/"thread"/"queue")."""
    out: Dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        ctor = qualname(node.value.func)
        tag = _TYPED_CTORS.get(ctor or "") or _TYPED_CTORS.get(qual_tail(ctor, 2))
        if not tag:
            continue
        for tgt in node.targets:
            q = qualname(tgt)
            if q and q.startswith("self."):
                out[q] = tag
    return out


def _module_lock_names(sf: SourceFile) -> Dict[str, str]:
    """Module-level NAME = threading.Lock()/Condition() assignments."""
    out: Dict[str, str] = {}
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            ctor = qualname(node.value.func)
            tag = _LOCK_CTORS.get(ctor or "") or _LOCK_CTORS.get(qual_tail(ctor, 2))
            if tag:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = tag
    return out


def _has_timeout(call: ast.Call) -> bool:
    if call.args:
        return True
    return call_kwarg(call, "timeout") is not None


def _blocking_reason(call: ast.Call, attr_types: Dict[str, str], held: List[str]) -> Optional[str]:
    """Why this call blocks, or None if it is fine under a lock."""
    qual = qualname(call.func)
    if not qual:
        return None
    if qual_tail(qual, 2) == "time.sleep":
        return "'time.sleep' stalls every thread contending for the lock"
    if qual.startswith("subprocess."):
        return f"subprocess call '{qual}' blocks on the child process"
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    recv_q = qualname(call.func.value)
    recv_type = attr_types.get(recv_q or "")
    if attr in _SOCKET_BLOCKING_METHODS:
        return f"socket I/O '.{attr}()' blocks on the peer"
    if attr == "connect" and recv_type is None and recv_q and "sock" in recv_q.lower():
        return "socket '.connect()' blocks on the peer"
    if attr == "block_until_ready":
        return "'.block_until_ready()' waits for device completion"
    if attr == "compile" and qual != "re.compile":
        return "XLA '.compile()' can take seconds"
    if attr == "get" and recv_type == "queue":
        if call_kwarg(call, "timeout") is None and not _is_nonblocking_get(call):
            return "unbounded 'queue.get()' can wait forever"
        return None
    if attr == "join" and recv_type == "thread":
        return "'.join()' waits for thread exit"
    if attr in ("wait", "wait_for"):
        if recv_type == "condition" and recv_q in held:
            return None  # Condition.wait on the held condition releases it: the designed use.
        if recv_type == "event" and not _has_timeout(call):
            return "unbounded 'Event.wait()' can wait forever"
        if recv_type == "condition" and recv_q not in held:
            return "waiting on a condition whose lock is not the held one"
    return None


def _is_nonblocking_get(call: ast.Call) -> bool:
    blk = call_kwarg(call, "block")
    if isinstance(blk, ast.Constant) and blk.value is False:
        return True
    if call.args and isinstance(call.args[0], ast.Constant) and call.args[0].value is False:
        return True
    return False


def _with_locks(node: ast.With, lock_names: Dict[str, str]) -> List[str]:
    out = []
    for item in node.items:
        q = qualname(item.context_expr)
        if q and q in lock_names:
            out.append(q)
    return out


def _scan_node(sf, owner, node, lock_names, attr_types, findings, edges, held) -> None:
    # Manual recursion (not ast.walk) so the held-lock stack nests with
    # `with` blocks and stops at function boundaries.
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return  # nested defs execute later, under unknown lock state
    if isinstance(node, ast.With):
        acquired = _with_locks(node, lock_names)
        for new in acquired:
            for h in held:
                if h != new:
                    edges.setdefault((h, new), (owner, node.lineno))
        inner = held + acquired
        for item in node.items:
            _scan_node(sf, owner, item.context_expr, lock_names, attr_types, findings, edges, held)
        for stmt in node.body:
            _scan_node(sf, owner, stmt, lock_names, attr_types, findings, edges, inner)
        return
    if isinstance(node, ast.Call) and held:
        reason = _blocking_reason(node, attr_types, held)
        if reason:
            findings.append(
                Finding(
                    "TPL021",
                    sf.rel,
                    node.lineno,
                    node.col_offset,
                    owner,
                    f"{reason} (holding {', '.join(held)})",
                )
            )
    for child in ast.iter_child_nodes(node):
        _scan_node(sf, owner, child, lock_names, attr_types, findings, edges, held)


def check(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for sf in ctx.files:
        module_locks = _module_lock_names(sf)
        # Module-level functions guard with module locks.
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and module_locks:
                edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
                for stmt in node.body:
                    _scan_node(sf, node.name, stmt, module_locks, {}, findings, edges, [])
        for cls in [n for n in ast.walk(sf.tree) if isinstance(n, ast.ClassDef)]:
            attr_types = _attr_types(cls)
            lock_names = {k: v for k, v in attr_types.items() if v in ("lock", "condition")}
            lock_names.update(module_locks)
            edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
            for meth in cls.body:
                if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                owner = f"{cls.name}.{meth.name}"
                for stmt in meth.body:
                    _scan_node(sf, owner, stmt, lock_names, attr_types, findings, edges, [])
            reported: Set[frozenset] = set()
            for (a, b), (owner, line) in edges.items():
                if (b, a) in edges:
                    pair = frozenset((a, b))
                    if pair in reported:
                        continue
                    reported.add(pair)
                    other_owner, other_line = edges[(b, a)]
                    findings.append(
                        Finding(
                            "TPL022",
                            sf.rel,
                            line,
                            0,
                            owner,
                            f"lock-order inversion: {owner} takes {a} then {b}, "
                            f"but {other_owner} (line {other_line}) takes {b} then {a}",
                        )
                    )
    return findings
