"""TPL03x — thread-lifecycle: every thread must be reclaimable.

A thread that is neither ``daemon=True`` nor joined anywhere keeps the
process alive after main exits; a ``while True`` service loop with no
``break``/``return`` can never be asked to stop.  Both patterns have bitten
this repo's serving stack (the batcher dispatcher, router accept loop and
decode scheduler all carry explicit stop wiring today — this checker keeps
it that way).

* TPL031 — ``threading.Thread(...)`` that is not ``daemon=True`` (at the
  constructor or via a later ``.daemon = True`` assignment) and whose
  binding (``self._t`` / local name) is never ``.join()``-ed in the file.
* TPL032 — a thread target containing a ``while True:`` loop with no
  ``break``, ``return`` or ``raise`` anywhere in the loop body: no code
  path can ever leave the loop, so stop()/drain can never conclude.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import AnalysisContext, Finding, SourceFile, call_kwarg, qual_tail, qualname

RULES = {
    "TPL031": "thread is neither daemon=True nor provably joined",
    "TPL032": "thread loop has no termination path (no break/return in 'while True')",
}


def _is_thread_ctor(call: ast.Call) -> bool:
    qual = qualname(call.func)
    return qual in ("threading.Thread", "Thread") or qual_tail(qual, 2) == "threading.Thread"


def _binding_of(sf: SourceFile, call: ast.Call) -> Optional[str]:
    """Qualname the Thread object is assigned to (``self._t`` / ``t``), or None."""
    parent = sf.parent(call)
    if isinstance(parent, ast.Assign):
        for tgt in parent.targets:
            q = qualname(tgt)
            if q:
                return q
    if isinstance(parent, (ast.AnnAssign, ast.AugAssign)):
        return qualname(parent.target)
    return None


def _joined_or_daemoned(sf: SourceFile, binding: str) -> bool:
    """True if ``binding.join(...)`` or ``binding.daemon = True`` appears anywhere."""
    for node in ast.walk(sf.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
            and qualname(node.func.value) == binding
        ):
            return True
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                q = qualname(tgt)
                if q == f"{binding}.daemon" and isinstance(node.value, ast.Constant) and node.value.value is True:
                    return True
    return False


def _resolve_target(sf: SourceFile, call: ast.Call) -> Optional[ast.AST]:
    tgt = call_kwarg(call, "target")
    if tgt is None:
        return None
    q = qualname(tgt)
    if not q:
        return None
    if q.startswith("self."):
        meth_name = q.split(".", 1)[1]
        cls = _enclosing_class(sf, call)
        if cls is not None:
            for node in cls.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node.name == meth_name:
                    return node
        return None
    for node in sf.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node.name == q:
            return node
    return None


def _enclosing_class(sf: SourceFile, node: ast.AST) -> Optional[ast.ClassDef]:
    cur = sf.parent(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = sf.parent(cur)
    return None


def _loop_can_exit(loop: ast.While) -> bool:
    """Any break/return/raise inside the loop (outside nested defs)?"""
    stack: List[ast.AST] = list(loop.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Break, ast.Return, ast.Raise)):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return False


def check(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for sf in ctx.files:
        checked_targets: Set[int] = set()
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                continue
            symbol = sf.enclosing_symbol(node)
            daemon = call_kwarg(node, "daemon")
            is_daemon = isinstance(daemon, ast.Constant) and daemon.value is True
            if not is_daemon:
                binding = _binding_of(sf, node)
                if binding is None or not _joined_or_daemoned(sf, binding):
                    where = f"'{binding}'" if binding else "an unbound thread"
                    findings.append(
                        Finding(
                            "TPL031",
                            sf.rel,
                            node.lineno,
                            node.col_offset,
                            symbol,
                            f"thread {where} is not daemon=True and is never joined — "
                            "it will outlive the process's intent to exit",
                        )
                    )
            target = _resolve_target(sf, node)
            if target is None or id(target) in checked_targets:
                continue
            checked_targets.add(id(target))
            for tnode in ast.walk(target):
                if isinstance(tnode, ast.While):
                    test = tnode.test
                    if isinstance(test, ast.Constant) and test.value is True and not _loop_can_exit(tnode):
                        findings.append(
                            Finding(
                                "TPL032",
                                sf.rel,
                                tnode.lineno,
                                tnode.col_offset,
                                getattr(target, "name", symbol),
                                "'while True' thread loop has no break/return — "
                                "no stop flag or sentinel can ever end it",
                            )
                        )
    return findings
