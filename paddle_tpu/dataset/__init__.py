"""paddle.dataset — the legacy dataset namespace (reference:
python/paddle/dataset/: mnist, cifar, imdb, imikolov, uci_housing,
movielens, conll05, wmt14 as per-module `train()/test()` generators).

This build's datasets live in `paddle.vision.datasets` and `paddle.text`
(zero-egress: local files or synthetic corpora); this namespace re-exposes
them with the legacy module-per-dataset shape so `paddle.dataset.mnist
.train()`-style code keeps working.
"""
from __future__ import annotations

import types as _types

__all__ = ["mnist", "cifar", "imdb", "imikolov", "uci_housing",
           "movielens", "conll05", "wmt14"]


def _reader_from(dataset_cls, **fixed):
    """Legacy reader creator: returns a generator fn over (fields...) —
    the reference's paddle.reader protocol. Positional args (the
    reference creators take vocab dicts / sizes, e.g. imdb.train(word_idx),
    imikolov.train(word_idx, n), wmt14.train(dict_size)) are accepted for
    signature compatibility and ignored: the zero-egress datasets build
    their own synthetic vocabularies."""
    def creator(*_legacy_args, **kw):
        ds = dataset_cls(**{**fixed, **kw})

        def reader():
            for i in range(len(ds)):
                yield tuple(ds[i])
        return reader
    return creator


def _module(name, dataset_cls, train_kw, test_kw):
    import sys
    m = _types.ModuleType(f"{__name__}.{name}")
    m.train = _reader_from(dataset_cls, **train_kw)
    m.test = _reader_from(dataset_cls, **test_kw)
    # register so the canonical legacy form works:
    #   import paddle_tpu.dataset.mnist
    sys.modules[m.__name__] = m
    return m


def _vision_reader(dataset_cls, image_shape, num_classes, mode):
    """Legacy creator for the vision sets: with local file paths use the
    real dataset; without (zero-egress default, where the reference would
    download) fall back to deterministic synthetic samples."""
    from ..vision.datasets import FakeData

    def creator(*_legacy_args, **kw):
        if kw:                       # user supplied local files
            ds = dataset_cls(mode=mode, **kw)
        else:
            # widely separated seeds: FakeData seeds per item with
            # seed+idx, so adjacent split seeds would alias samples
            ds = FakeData(num_samples=512, image_shape=image_shape,
                          num_classes=num_classes,
                          seed=0 if mode == "train" else 1_000_000)

        def reader():
            for i in range(len(ds)):
                yield tuple(ds[i])
        return reader
    return creator


def _vision_module(name, dataset_cls, image_shape, num_classes):
    import sys
    m = _types.ModuleType(f"{__name__}.{name}")
    m.train = _vision_reader(dataset_cls, image_shape, num_classes, "train")
    m.test = _vision_reader(dataset_cls, image_shape, num_classes, "test")
    sys.modules[m.__name__] = m
    return m


def _cifar_module():
    """The reference cifar module's surface is train10/test10/train100/
    test100 (python/paddle/dataset/cifar.py); train/test alias the -10
    variants for convenience."""
    import sys

    from ..vision.datasets import Cifar10, Cifar100
    m = _types.ModuleType(f"{__name__}.cifar")
    m.train10 = _vision_reader(Cifar10, (3, 32, 32), 10, "train")
    m.test10 = _vision_reader(Cifar10, (3, 32, 32), 10, "test")
    m.train100 = _vision_reader(Cifar100, (3, 32, 32), 100, "train")
    m.test100 = _vision_reader(Cifar100, (3, 32, 32), 100, "test")
    m.train, m.test = m.train10, m.test10
    sys.modules[m.__name__] = m
    return m


def _build():
    from ..text import (Conll05st, Imdb, Imikolov, Movielens, UCIHousing,
                        WMT14)
    from ..vision.datasets import MNIST

    mods = {
        "mnist": _vision_module("mnist", MNIST, (1, 28, 28), 10),
        "cifar": _cifar_module(),
        "imdb": _module("imdb", Imdb,
                        {"mode": "train"}, {"mode": "test"}),
        "imikolov": _module("imikolov", Imikolov,
                            {"mode": "train"}, {"mode": "test"}),
        "uci_housing": _module("uci_housing", UCIHousing,
                               {"mode": "train"}, {"mode": "test"}),
        "movielens": _module("movielens", Movielens,
                             {"mode": "train"}, {"mode": "test"}),
        "conll05": _module("conll05", Conll05st,
                   {"mode": "train"}, {"mode": "test"}),
        "wmt14": _module("wmt14", WMT14,
                         {"mode": "train"}, {"mode": "test"}),
    }
    return mods


_mods = _build()
globals().update(_mods)
