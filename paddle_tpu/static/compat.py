"""paddle.static compatibility surface over the trace-based design.

Reference: python/paddle/static/__init__.py re-exports the Program/
Executor machinery (fluid/framework.py Program:4458, executor.py
Executor:779, io.py save/load_inference_model). In this framework the
"program" IS a traced callable (StaticFunction / exported StableHLO),
so each name here maps onto that design with REAL behavior:

- Executor.run drives callables, StaticFunction and loaded
  TranslatedLayer programs with feed/fetch dicts;
- save/load_inference_model and the (de)serialize helpers are the
  jit.save/jit.load artifacts ({path}.pdmodel/.pdiparams);
- gradients/append_backward are the tape's autograd surface;
- accuracy/auc are the static metric ops as direct math;
- Program/Scope/program_guard keep the structural API (a Program
  records the layers/fetches the Executor binds; a Scope is the
  name->Tensor dict feed/fetch resolve against).
"""
from __future__ import annotations

import contextlib
import dataclasses

import numpy as np

from ..core.tensor import Tensor, to_tensor

__all__ = [
    "Program", "CompiledProgram", "BuildStrategy", "ExecutionStrategy",
    "Executor", "ParallelExecutor", "Scope", "Variable", "global_scope",
    "scope_guard", "program_guard", "default_main_program",
    "default_startup_program", "name_scope", "device_guard",
    "cpu_places", "cuda_places", "xpu_places", "gradients",
    "append_backward", "py_func", "Print", "accuracy", "auc",
    "save", "load", "save_inference_model", "load_inference_model",
    "serialize_program", "deserialize_program", "serialize_persistables",
    "deserialize_persistables", "save_to_file", "load_from_file",
    "normalize_program", "save_vars", "load_vars", "load_program_state",
    "set_program_state", "WeightNormParamAttr",
]

Variable = Tensor          # the eager Tensor IS the variable


class Scope:
    """Name -> Tensor binding the Executor resolves feeds/fetches
    against (reference Scope; here a plain dict)."""

    def __init__(self):
        self.vars = {}

    def var(self, name):
        return self.vars.setdefault(name, None)

    def find_var(self, name):
        return self.vars.get(name)

    def set(self, name, value):
        self.vars[name] = value


_GLOBAL_SCOPE = Scope()
_SCOPE_STACK = [_GLOBAL_SCOPE]


def global_scope():
    return _SCOPE_STACK[-1]


@contextlib.contextmanager
def scope_guard(scope):
    _SCOPE_STACK.append(scope)
    try:
        yield
    finally:
        _SCOPE_STACK.pop()


class Program:
    """A runnable unit: callables/layers registered (or passed straight
    to Executor.run). The startup program's job — parameter init —
    already happened eagerly at layer construction, so running it is a
    no-op by design (documented), not an omission."""

    def __init__(self):
        self._callables = []
        self._parameters = {}    # static.nn ops register implicit params
        self._buffers = {}       # non-trainable stats (moving mean/var)
        self.random_seed = None

    def add(self, fn):
        self._callables.append(fn)
        return fn

    def all_parameters(self):
        """Implicitly created static.nn TRAINABLE parameters (reference
        Program.all_parameters) — feed these to an optimizer. Running
        statistics (batch_norm moving mean/var, data_norm accumulators)
        live in the buffer table instead: the reference keeps them as
        persistable non-parameter variables, so an optimizer never
        weight-decays them."""
        return list(self._parameters.values())

    def all_buffers(self):
        """Non-trainable running statistics registered by static.nn ops
        (persistable in the reference, excluded from all_parameters)."""
        return list(self._buffers.values())

    def global_block(self):
        return self

    # block API subset used by porting code
    @property
    def ops(self):
        return list(self._callables)

    def clone(self, for_test=False):
        p = Program()
        p._callables = list(self._callables)
        p._parameters = dict(self._parameters)
        p._buffers = dict(self._buffers)
        return p


_MAIN = Program()
_STARTUP = Program()


def default_main_program():
    return _MAIN


def default_startup_program():
    return _STARTUP


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _MAIN, _STARTUP
    old = (_MAIN, _STARTUP)
    _MAIN = main_program
    if startup_program is not None:
        _STARTUP = startup_program
    try:
        yield
    finally:
        _MAIN, _STARTUP = old


@dataclasses.dataclass
class BuildStrategy:
    """Build hints (reference BuildStrategy): XLA owns fusion/memory
    passes, so these are accepted-and-recorded toggles."""
    enable_inplace: bool = True
    fuse_all_optimizer_ops: bool = False
    fuse_elewise_add_act_ops: bool = False
    memory_optimize: bool = True
    reduce_strategy: int = 0


@dataclasses.dataclass
class ExecutionStrategy:
    num_threads: int = 1
    num_iteration_per_drop_scope: int = 100


class CompiledProgram:
    """CompiledProgram(program-or-callable).with_data_parallel analog:
    binding happens at Executor.run; jit compilation is the engine."""

    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        if build_strategy is not None:
            self.build_strategy = build_strategy
        return self


class Executor:
    """Runs callables / StaticFunction / jit.load programs with
    feed/fetch dicts (reference executor.py:779). The callable's
    positional order defines the feed binding: feed keys are matched by
    the callable's signature when available, else by sorted key."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            scope=None, return_numpy=True):
        feed = feed or {}
        scope = scope or global_scope()
        if program is None or program is _STARTUP or (
                isinstance(program, Program) and not program._callables):
            return []            # startup: params were eagerly initialized
        target = program.program if isinstance(program, CompiledProgram) \
            else program
        runners = (target._callables if isinstance(target, Program)
                   else [target])
        import inspect as _inspect
        outs = []
        for fn in runners:
            args = []
            try:
                sig = _inspect.signature(getattr(fn, "forward", fn))
                params = [p for n_, p in sig.parameters.items()
                          if n_ != "self"]
                var_positional = any(
                    p.kind is _inspect.Parameter.VAR_POSITIONAL
                    for p in params)
                names = [p.name for p in params
                         if p.kind in (_inspect.Parameter.POSITIONAL_ONLY,
                                       _inspect.Parameter
                                       .POSITIONAL_OR_KEYWORD)]
            except (TypeError, ValueError):
                var_positional, names = True, []
            bound = [n for n in names if n in feed]
            if bound:
                args = [to_tensor(np.asarray(feed[n])) for n in bound]
            elif feed:
                # no name matched (or *args callable): feed values bind
                # positionally in sorted-key order — the reference feeds
                # by placeholder name; here a traced callable's params
                # may be named differently than the user's feed keys
                args = [to_tensor(np.asarray(feed[k]))
                        for k in sorted(feed)]
            out = fn(*args)
            outs.append(out)
            scope.set(getattr(out, "name", None) or f"fetch_{len(outs)}",
                      out)
        if fetch_list:
            res = []
            for f in fetch_list:
                v = f if isinstance(f, Tensor) else scope.find_var(str(f))
                if v is None:
                    raise KeyError(
                        f"fetch target {f!r} not found in the scope "
                        "(pass the Tensor itself, or set() it on the "
                        "scope) — the reference Executor raises on "
                        "unknown fetches too")
                res.append(np.asarray(v.numpy()) if return_numpy and
                           hasattr(v, "numpy") else v)
            return res
        if return_numpy:
            return [np.asarray(o.numpy()) if hasattr(o, "numpy") else o
                    for o in outs]
        return outs

    def close(self):
        pass


ParallelExecutor = Executor      # jit SPMD steps are the parallel engine


def cpu_places(device_count=None):
    import jax
    n = device_count or max(1, len([d for d in jax.devices()
                                    if d.platform == "cpu"]) or 1)
    from ..core.place import CPUPlace
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    import jax
    from ..core.place import TPUPlace
    ids = device_ids if device_ids is not None else \
        range(len(jax.devices()))
    return [TPUPlace(i) for i in ids]


xpu_places = cuda_places


@contextlib.contextmanager
def name_scope(prefix=None):
    """Name prefix context (reference fluid.name_scope); eager Tensors
    carry generated names, so this is an annotation scope."""
    yield


@contextlib.contextmanager
def device_guard(device=None):
    """Op placement hint (reference device_guard); XLA places ops, so
    the hint is accepted without effect."""
    yield


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """d(targets)/d(inputs) via the tape (reference append_backward
    machinery -> here core.autograd.grad)."""
    from ..core.autograd import grad as _grad
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    total = targets[0]
    for t in targets[1:]:
        total = total + t
    return _grad([total], list(inputs),
                 grad_outputs=target_gradients, allow_unused=True)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Populate .grad on the parameters reaching `loss` (reference
    backward.py append_backward). Returns [(param, grad)] pairs."""
    loss.backward()
    params = parameter_list or []
    if not params:
        return []
    out = []
    for p in params:
        out.append((p, p.grad))
    return out


def py_func(func, x, out=None, backward_func=None, skip_vars_in_backward_input=None):
    """Run a python function as an op (reference py_func_op): eager call
    with Tensor(in)/Tensor(out) conversion; the tape handles backward
    when `func` is built from framework ops, else it is a constant."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    res = func(*xs)
    if isinstance(res, (list, tuple)):
        return [r if isinstance(r, Tensor) else to_tensor(np.asarray(r))
                for r in res]
    return res if isinstance(res, Tensor) else to_tensor(np.asarray(res))


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug print op (reference Print): prints and passes through."""
    arr = np.asarray(input.numpy()) if hasattr(input, "numpy") else \
        np.asarray(input)
    head = f"{message or 'Print'}:"
    if print_tensor_shape:
        head += f" shape={list(arr.shape)}"
    if print_tensor_type:
        head += f" dtype={arr.dtype}"
    flat = arr.reshape(-1)[:max(int(summarize), 0) or None]
    print(head, flat)
    return input


def accuracy(input, label, k=1, correct=None, total=None):
    """Static accuracy op (reference layers.accuracy): top-k hit rate."""
    from ..metric import Accuracy
    m = Accuracy(topk=(k,))
    corr = m.compute(input, label)
    res = m.update(corr)
    return to_tensor(np.asarray(res, np.float32))


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Static AUC op (reference layers.auc): area under the ROC curve of
    the positive-class scores."""
    from ..metric import Auc
    m = Auc(curve=curve, num_thresholds=num_thresholds)
    m.update(input, label)
    return to_tensor(np.asarray(m.accumulate(), np.float32))


# -- save/load surface over the jit artifacts --------------------------------

def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kw):
    """Export for serving (reference static/io.py save_inference_model):
    `fetch_vars` is the layer/StaticFunction; feed_vars supply the
    InputSpecs (the jit.save artifact pair)."""
    from .. import jit as jit_mod
    from . import InputSpec
    target = fetch_vars
    if isinstance(target, (list, tuple)):
        if len(target) != 1:
            raise ValueError("save_inference_model here exports ONE "
                             "callable (the traced program)")
        target = target[0]
    specs = [f if isinstance(f, InputSpec) else
             InputSpec(list(getattr(f, "shape", [None])),
                       str(getattr(f, "dtype", "float32")))
             for f in (feed_vars if isinstance(feed_vars, (list, tuple))
                       else [feed_vars])]
    jit_mod.save(target, path_prefix, input_spec=specs)
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kw):
    """Load a served program (reference load_inference_model). Returns
    (program, feed_names, fetch_names) with `program` a callable."""
    from .. import jit as jit_mod
    prog = jit_mod.load(path_prefix)
    return prog, [], []


def serialize_program(feed_vars=None, fetch_vars=None, program=None,
                      path=None, layer=None, input_spec=None):
    """Program bytes = the exported StableHLO module (jit.save's
    .pdmodel payload) for a layer/StaticFunction."""
    import os
    import tempfile

    from .. import jit as jit_mod
    from . import InputSpec
    target = fetch_vars or layer or program
    specs = input_spec
    if specs is None and feed_vars is not None:
        fv = feed_vars if isinstance(feed_vars, (list, tuple)) \
            else [feed_vars]
        specs = [f if isinstance(f, InputSpec) else
                 InputSpec(list(getattr(f, "shape", [None])),
                           str(getattr(f, "dtype", "float32")))
                 for f in fv]
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "m")
        jit_mod.save(target, p, input_spec=specs)
        with open(p + ".pdmodel", "rb") as f:
            return f.read()


def deserialize_program(data):
    from jax import export as jax_export
    return jax_export.deserialize(data)


def serialize_persistables(feed_vars=None, fetch_vars=None, program=None,
                           layer=None):
    import pickle

    target = fetch_vars or layer or program
    params = {k: np.asarray(v.numpy())
              for k, v in dict(target.named_parameters()).items()}
    return pickle.dumps(params, protocol=4)


def deserialize_persistables(program_or_layer, data, executor=None):
    import pickle

    params = pickle.loads(data)
    lookup = dict(program_or_layer.named_parameters())
    for k, v in params.items():
        if k in lookup:
            lookup[k].set_value(v)
    return program_or_layer


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars=None, fetch_vars=None):
    """Inference-ready form (reference prunes feed/fetch ops); traced
    programs are already minimal — identity."""
    return program


def save_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None):
    """Persist named Tensors (reference io.save_vars) as one pickle."""
    import os
    import pickle

    payload = {getattr(v, "name", f"var_{i}"): np.asarray(v.numpy())
               for i, v in enumerate(vars or [])}
    path = os.path.join(dirname or ".", filename or "vars.pkl")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(payload, f, protocol=4)
    return path


def load_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None):
    import os
    import pickle

    path = os.path.join(dirname or ".", filename or "vars.pkl")
    with open(path, "rb") as f:
        payload = pickle.load(f)
    for v in vars or []:
        n = getattr(v, "name", None)
        if n in payload:
            v.set_value(payload[n])
    return payload


def load_program_state(model_path, var_list=None):
    """state dict from a framework save (reference
    load_program_state over .pdparams)."""
    from ..framework import load as _load
    return _load(model_path if model_path.endswith(".pdparams")
                 else model_path + ".pdparams")


def set_program_state(program_or_layer, state):
    lookup = dict(program_or_layer.named_parameters())
    for k, v in state.items():
        if k in lookup:
            lookup[k].set_value(np.asarray(v))


def save(program_or_layer, path, **kw):
    """static.save -> framework save of the layer's state
    (reference static/io.py save)."""
    from ..framework import save as _save
    _save(dict(program_or_layer.named_parameters()) if hasattr(
        program_or_layer, "named_parameters") else program_or_layer,
        path if path.endswith(".pdparams") else path + ".pdparams")


def load(program_or_layer, path, executor=None, var_list=None):
    state = load_program_state(path)
    set_program_state(program_or_layer, state)
    return state


class WeightNormParamAttr:
    """ParamAttr marker requesting weight normalization (reference
    WeightNormParamAttr); consumed by applying nn.weight_norm to the
    owning layer with the recorded dim."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip
