"""paddle.static surface.

The reference's static-graph entry (python/paddle/static/) carries the
Program/Executor machinery; on TPU the program IS the jitted/exported
StableHLO module, so this module keeps only the pieces with meaning here:
InputSpec (shape/dtype declarations for jit.save / to_static) and thin
aliases onto the jit path.
"""
from __future__ import annotations

from ..core import dtype as dtype_mod
from . import nn  # noqa: F401  (cond/case/switch_case/while_loop)

from .compat import *  # noqa: F401,F403
from ..legacy_alias import create_global_var, create_parameter  # noqa: F401
from .compat import __all__ as _compat_all
from . import amp  # noqa: F401  (static/amp.py: the amp surface
# + the reference's mixed_precision/bf16 sub-names)

__all__ = ["InputSpec", "nn", "data", "amp"] + list(_compat_all)


def data(name, shape, dtype="float32", lod_level=0):
    """Static input declaration (reference python/paddle/static/input.py
    data): under the jit/export path a placeholder IS an InputSpec; -1
    dims become None (dynamic until trace time)."""
    shape = [None if (s is None or int(s) < 0) else int(s) for s in shape]
    return InputSpec(shape, dtype=dtype, name=name)


class InputSpec:
    """Declares one input's (shape, dtype, name); None dims are symbolic
    (exported modules accept any size there). Reference:
    python/paddle/static/input.py InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor.shape), str(tensor.dtype), name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")
