"""paddle.static.nn op layer — fluid-1.x-style functions with implicit
parameters.

Reference: python/paddle/static/nn/__init__.py:15-42 re-exports the
fluid layer functions (fluid/layers/nn.py fc:87, conv2d:1411,
batch_norm:2744, layer_norm:3015, ...) which create parameters in the
startup program's global block and append ops to the main program.

TPU-native redesign: the eager Tensor IS the variable and jit tracing IS
the program, so each op here (a) resolves/creates its parameters in a
process-wide *static parameter scope* — same fluid semantics: a
`ParamAttr(name=...)` shared between two calls shares the weights, an
anonymous call gets a fresh `{op}_{i}.w_0`-style name — and (b) computes
the result immediately through the existing nn.functional kernels. The
created parameters register on `default_main_program()` so
`program.all_parameters()` feeds optimizers exactly like reference
static-graph code expects.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply, to_tensor  # noqa: F401
from ..framework import ParamAttr

__all__ = [
    "fc", "batch_norm", "embedding", "bilinear_tensor_product", "conv2d",
    "conv2d_transpose", "conv3d", "conv3d_transpose", "create_parameter",
    "crf_decoding", "data_norm", "deform_conv2d", "group_norm",
    "instance_norm", "layer_norm", "multi_box_head", "nce", "prelu",
    "py_func", "row_conv", "spectral_norm", "sparse_embedding",
]


# -- the static parameter scope ----------------------------------------------

_PARAMS: dict = {}
_COUNTERS: dict = {}


def _unique(prefix):
    i = _COUNTERS.get(prefix, 0)
    _COUNTERS[prefix] = i + 1
    return f"{prefix}_{i}"


def reset_parameter_scope():
    """Drop every implicitly created parameter (test isolation; the
    reference analog is a fresh startup Program)."""
    _PARAMS.clear()
    _COUNTERS.clear()


def parameter_scope():
    return dict(_PARAMS)


def _param(name, shape, dtype, attr, is_bias=False, default_init=None,
           is_buffer=False):
    """Fluid create-or-share: an attr-named parameter that already exists
    is reused (shape-checked); otherwise a new one is created under
    `name` and registered on the scope + default main program.

    `is_buffer` marks non-trainable running statistics (batch_norm
    moving mean/var, data_norm accumulators): they stay addressable by
    name in the scope but register on the program's BUFFER table, so
    `Program.all_parameters()` never hands them to an optimizer (the
    reference keeps them as persistable non-parameter variables — an
    optimizer applying weight decay to running stats would corrupt
    them)."""
    from ..legacy_alias import create_parameter as _create
    attr = ParamAttr._to_attr(attr)
    if attr is False:
        return None
    pname = attr.name or name
    if pname in _PARAMS:
        p = _PARAMS[pname]
        if tuple(int(s) for s in p.shape) != tuple(int(s) for s in shape):
            raise ValueError(
                f"static.nn parameter {pname!r} exists with shape "
                f"{tuple(p.shape)}, requested {tuple(shape)}")
        return p
    p = _create(shape, dtype=dtype, name=pname, attr=attr, is_bias=is_bias,
                default_initializer=default_init)
    p.name = pname
    _PARAMS[pname] = p
    prog = _default_program()
    if prog is not None:
        if is_buffer:
            prog._buffers[pname] = p
        else:
            prog._parameters[pname] = p
    return p


def _default_program():
    from .compat import default_main_program
    try:
        return default_main_program()
    except Exception:
        return None


def _act(out, act):
    if act is None:
        return out
    from ..nn import functional as F
    fn = getattr(F, act, None)
    if fn is None:
        raise ValueError(f"unknown activation {act!r}")
    return fn(out)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """paddle.static.nn.create_parameter (fluid/layers/tensor.py) — the
    scope-registered variant of the top-level helper."""
    return _param(name or _unique("create_parameter") + ".w_0",
                  shape, dtype, attr, is_bias=is_bias,
                  default_init=default_initializer)


# -- dense / embedding --------------------------------------------------------

def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Fully-connected over flattened trailing dims (reference
    static/nn/common.py fc): each input gets its own weight; outputs
    sum before one shared bias + activation."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    base = name or _unique("fc")
    out = None
    for i, xi in enumerate(xs):
        shp = tuple(int(s) for s in xi.shape)
        nfd = num_flatten_dims if num_flatten_dims >= 0 else len(shp) - 1
        in_dim = int(np.prod(shp[nfd:]))
        w = _param(f"{base}.w_{i}", (in_dim, size), str(xi.dtype),
                   weight_attr)
        flat = xi.reshape(list(shp[:nfd]) + [in_dim])
        term = flat.matmul(w)
        out = term if out is None else out + term
    b = _param(f"{base}.b_0", (size,), str(xs[0].dtype), bias_attr,
               is_bias=True)
    if b is not None:
        out = out + b
    return _act(out, activation)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """Lookup-table op (reference fluid/input.py embedding)."""
    from ..nn import functional as F
    w = _param(_unique("embedding") + ".w_0", tuple(size), dtype,
               param_attr)
    return F.embedding(input, w, padding_idx=padding_idx, sparse=is_sparse)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="CommonSparseTable",
                     param_attr=None, dtype="float32"):
    """PS-backed large-vocab embedding (reference
    fluid/contrib/layers/sparse_embedding): on TPU the table lives
    sharded in HBM and the lookup is the same gather — the PS
    distribution strategy (distributed/ps) shards it at scale."""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """out_k = x^T W_k y + b_k (reference fluid/layers/nn.py
    bilinear_tensor_product)."""
    from ..nn import functional as F
    d1, d2 = int(x.shape[-1]), int(y.shape[-1])
    base = name or _unique("bilinear_tensor_product")
    w = _param(f"{base}.w_0", (size, d1, d2), str(x.dtype), param_attr)
    b = _param(f"{base}.b_0", (1, size), str(x.dtype), bias_attr,
               is_bias=True)
    out = F.bilinear_tensor_product(x, y, w, b)
    return _act(out, act)


# -- convolutions -------------------------------------------------------------

def _filter_tuple(filter_size, n):
    if isinstance(filter_size, (list, tuple)):
        return tuple(int(k) for k in filter_size)
    return (int(filter_size),) * n


def _conv_nd(n, op_name, input, num_filters, filter_size, stride, padding,
             dilation, groups, param_attr, bias_attr, act, data_format,
             transpose=False, output_size=None, output_padding=0):
    from ..nn import functional as F
    groups = groups or 1
    channels_last = not data_format.startswith("NC")
    c_in = int(input.shape[-1] if channels_last else input.shape[1])
    k = _filter_tuple(filter_size, n)
    if transpose:
        # reference transpose-conv weight layout: [in_c, out_c/groups, *k]
        wshape = (c_in, num_filters // groups) + k
    else:
        wshape = (num_filters, c_in // groups) + k
    base = _unique(op_name)
    fan_in = int(np.prod((c_in // groups,) + k))
    from ..nn import initializer as I
    w = _param(f"{base}.w_0", wshape, str(input.dtype), param_attr,
               default_init=I.Normal(0.0, float(np.sqrt(2.0 / fan_in))))
    b = _param(f"{base}.b_0", (num_filters,), str(input.dtype), bias_attr,
               is_bias=True)
    fn = getattr(F, f"conv{n}d_transpose" if transpose else f"conv{n}d")
    kw = dict(stride=stride, padding=padding, dilation=dilation,
              groups=groups, data_format=data_format)
    if transpose:
        kw["output_size"] = output_size
        kw["output_padding"] = output_padding
    out = fn(input, w, b, **kw)
    return _act(out, act)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    """fluid/layers/nn.py conv2d: implicit [O, I/g, kh, kw] filter."""
    return _conv_nd(2, name or "conv2d", input, num_filters, filter_size,
                    stride, padding, dilation, groups, param_attr,
                    bias_attr, act, data_format)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    return _conv_nd(3, name or "conv3d", input, num_filters, filter_size,
                    stride, padding, dilation, groups, param_attr,
                    bias_attr, act, data_format)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    """fluid/layers/nn.py conv2d_transpose. One of output_size /
    filter_size must be given; filter_size derives from output_size the
    reference way when absent."""
    if filter_size is None:
        if output_size is None:
            raise ValueError("conv2d_transpose: output_size and "
                             "filter_size cannot both be None")
        out = _filter_tuple(output_size, 2)
        channels_last = not data_format.startswith("NC")
        sp = input.shape[1:-1] if channels_last else input.shape[2:]
        stride_t = _filter_tuple(stride, 2)
        pad_t = _filter_tuple(padding, 2) if not isinstance(
            padding, str) else (0, 0)
        dil_t = _filter_tuple(dilation, 2)
        filter_size = tuple(
            (out[i] - (int(sp[i]) - 1) * stride_t[i] + 2 * pad_t[i] - 1)
            // dil_t[i] + 1 for i in range(2))
    return _conv_nd(2, name or "conv2d_transpose", input, num_filters,
                    filter_size, stride, padding, dilation, groups,
                    param_attr, bias_attr, act, data_format,
                    transpose=True, output_size=output_size)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    if filter_size is None:
        raise ValueError("conv3d_transpose requires filter_size")
    return _conv_nd(3, name or "conv3d_transpose", input, num_filters,
                    filter_size, stride, padding, dilation, groups,
                    param_attr, bias_attr, act, data_format,
                    transpose=True, output_size=output_size)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, weight_attr=None, bias_attr=None,
                  name=None):
    """static/nn/common.py deform_conv2d over the functional
    deformable_conv kernel (v2 when mask is given, v1 when None)."""
    from ..nn import functional as F
    c_in = int(x.shape[1])
    k = _filter_tuple(filter_size, 2)
    base = name or _unique("deform_conv2d")
    w = _param(f"{base}.w_0", (num_filters, c_in // (groups or 1)) + k,
               str(x.dtype), weight_attr)
    b = _param(f"{base}.b_0", (num_filters,), str(x.dtype), bias_attr,
               is_bias=True)
    return F.deformable_conv(x, offset, mask, num_filters, k, w, bias=b,
                             stride=stride, padding=padding,
                             dilation=dilation, groups=groups or 1,
                             deformable_groups=deformable_groups,
                             im2col_step=im2col_step,
                             modulated=mask is not None)


# -- normalization ------------------------------------------------------------

def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None,
               do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    """fluid/layers/nn.py batch_norm: implicit scale/bias + moving
    mean/variance; training mode updates the moving stats in place."""
    from ..nn import functional as F
    channels_last = not data_layout.startswith("NC")
    c = int(input.shape[-1 if channels_last else 1])
    base = name or _unique("batch_norm")
    from ..nn import initializer as I
    w = _param(f"{base}.w_0", (c,), "float32", param_attr,
               default_init=I.Constant(1.0))
    b = _param(f"{base}.b_0", (c,), "float32", bias_attr, is_bias=True)
    mean = _param(moving_mean_name or f"{base}.w_1", (c,), "float32", None,
                  default_init=I.Constant(0.0), is_buffer=True)
    var = _param(moving_variance_name or f"{base}.w_2", (c,), "float32",
                 None, default_init=I.Constant(1.0), is_buffer=True)
    mean.stop_gradient = True
    var.stop_gradient = True
    out = F.batch_norm(input, mean, var, weight=w, bias=b,
                       training=not is_test, momentum=momentum,
                       epsilon=epsilon, data_format=data_layout,
                       use_global_stats=use_global_stats)
    return _act(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """fluid/layers/nn.py layer_norm: normalize over
    dims[begin_norm_axis:], flat [prod(norm_dims)] scale/shift."""
    from ..nn import functional as F
    from ..nn import initializer as I
    shp = tuple(int(s) for s in input.shape)
    norm_shape = shp[begin_norm_axis:]
    base = name or _unique("layer_norm")
    w = _param(f"{base}.w_0", (int(np.prod(norm_shape)),), "float32",
               param_attr, default_init=I.Constant(1.0)) if scale else None
    b = _param(f"{base}.b_0", (int(np.prod(norm_shape)),), "float32",
               bias_attr, is_bias=True) if shift else None
    wr = w.reshape(list(norm_shape)) if w is not None else None
    br = b.reshape(list(norm_shape)) if b is not None else None
    out = F.layer_norm(input, list(norm_shape), weight=wr, bias=br,
                       epsilon=epsilon)
    return _act(out, act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    from ..nn import functional as F
    from ..nn import initializer as I
    channels_last = not data_layout.startswith("NC")
    c = int(input.shape[-1 if channels_last else 1])
    base = name or _unique("group_norm")
    w = _param(f"{base}.w_0", (c,), "float32", param_attr,
               default_init=I.Constant(1.0))
    b = _param(f"{base}.b_0", (c,), "float32", bias_attr, is_bias=True)
    out = F.group_norm(input, groups, epsilon=epsilon, weight=w, bias=b,
                       data_format=data_layout)
    return _act(out, act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from ..nn import functional as F
    from ..nn import initializer as I
    c = int(input.shape[1])
    base = name or _unique("instance_norm")
    w = _param(f"{base}.w_0", (c,), "float32", param_attr,
               default_init=I.Constant(1.0))
    b = _param(f"{base}.b_0", (c,), "float32", bias_attr, is_bias=True)
    return F.instance_norm(input, weight=w, bias=b, eps=epsilon)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              batch_size_default=1e4, batch_sum_default=0.0,
              batch_square_sum_default=1e4, slot_dim=-1, sync_stats=False,
              summary_decay_rate=0.9999999, enable_scale_and_shift=False):
    """fluid/layers/nn.py data_norm (kernel data_norm_op.cc): normalize
    by accumulated batch statistics — mean = batch_sum / batch_size,
    scale = sqrt(batch_size / batch_square_sum) with NO mean^2
    subtraction (the reference kernel normalizes by the raw second
    moment, data_norm_op.cc:303) — then fold the current batch into the
    accumulators with `summary_decay_rate`."""
    from ..nn import initializer as I
    c = int(input.shape[-1])
    base = name or _unique("data_norm")
    bsize = _param(f"{base}.batch_size", (c,), "float32", None,
                   default_init=I.Constant(float(batch_size_default)),
                   is_buffer=True)
    bsum = _param(f"{base}.batch_sum", (c,), "float32", None,
                  default_init=I.Constant(float(batch_sum_default)),
                  is_buffer=True)
    bsq = _param(f"{base}.batch_square_sum", (c,), "float32", None,
                 default_init=I.Constant(float(batch_square_sum_default)),
                 is_buffer=True)
    for p in (bsize, bsum, bsq):
        p.stop_gradient = True
    mean = bsum / bsize
    scale = bsize / (bsq + epsilon)
    out = (input - mean) * scale.sqrt()
    if enable_scale_and_shift:
        w = _param(f"{base}.w_0", (c,), "float32", param_attr,
                   default_init=I.Constant(1.0))
        b = _param(f"{base}.b_0", (c,), "float32", None, is_bias=True)
        out = out * w + b
    # fold the batch into the summaries (reference decay update)
    n = int(np.prod(input.shape[:-1]))
    d = float(summary_decay_rate)
    x = input.detach() if hasattr(input, "detach") else input
    bsize.set_value((bsize * d + float(n)).numpy())
    bsum.set_value((bsum * d + x.sum(axis=tuple(
        range(x.ndim - 1)))).numpy())
    bsq.set_value((bsq * d + (x * x).sum(axis=tuple(
        range(x.ndim - 1)))).numpy())
    return _act(out, act)


# -- sequence / misc ops ------------------------------------------------------

def crf_decoding(input, param_attr, label=None, length=None):
    """Viterbi decode against the shared CRF transition parameter
    (reference fluid/layers/nn.py crf_decoding; the transition is the
    one linear_chain_crf trains, addressed by param_attr name)."""
    from ..nn import functional as F
    tag_num = int(input.shape[-1])
    w = _param(_unique("crfw"), (tag_num + 2, tag_num), "float32",
               param_attr)
    return F.crf_decoding(input, w, label=label, length=length)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """fluid/layers/nn.py nce over the functional NCE kernel."""
    from ..nn import functional as F
    d = int(input.shape[-1])
    base = name or _unique("nce")
    w = _param(f"{base}.w_0", (num_total_classes, d), str(input.dtype),
               param_attr)
    b = _param(f"{base}.b_0", (num_total_classes,), str(input.dtype),
               bias_attr, is_bias=True)
    return F.nce(input, label, num_total_classes, w, bias=b,
                 sample_weight=sample_weight,
                 num_neg_samples=num_neg_samples or 10, sampler=sampler,
                 custom_dist=custom_dist, seed=seed)


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    """fluid/layers/nn.py prelu: mode in {'all','channel','element'}
    sizes the implicit alpha."""
    from ..nn import functional as F
    from ..nn import initializer as I
    if mode == "all":
        shape = (1,)
    elif mode == "channel":
        shape = (int(x.shape[1 if data_format.startswith("NC")
                             else -1]),)
    elif mode == "element":
        shape = tuple(int(s) for s in x.shape[1:])
    else:
        raise ValueError("prelu mode must be 'all'|'channel'|'element'")
    base = name or _unique("prelu")
    alpha = _param(f"{base}.w_0", shape, str(x.dtype), param_attr,
                   default_init=I.Constant(0.25))
    if mode == "element":
        return apply(lambda a, al: jnp.where(a > 0, a, al[None] * a),
                     x, alpha)
    return F.prelu(x, alpha, data_format=data_format)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (fluid/layers/nn.py row_conv; kernel
    row_conv_op.cc): out[t] = sum_{i=0..k} in[t+i] * w[i] per channel,
    for [B, T, D] batched input."""
    d = int(input.shape[-1])
    k = int(future_context_size)
    w = _param(_unique("row_conv") + ".w_0", (k + 1, d),
               str(input.dtype), param_attr)

    def f(a, wt):
        # pad T future steps with zeros, window-sum the lookahead
        pad = [(0, 0)] * a.ndim
        pad[-2] = (0, k)
        ap = jnp.pad(a, pad)
        out = jnp.zeros_like(a)
        for i in range(k + 1):
            out = out + ap[..., i:i + a.shape[-2], :] * wt[i]
        return out

    return _act(apply(f, input, w), act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """fluid/layers/nn.py spectral_norm — stateless power iteration over
    the given weight (the functional kernel)."""
    from ..nn import functional as F
    return F.spectral_norm(weight, dim=dim, power_iters=power_iters,
                           eps=eps)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD head (fluid/layers/detection.py multi_box_head): implicit
    per-level loc/conf conv parameters + prior boxes, over the
    functional kernel (which takes the weights explicitly)."""
    from ..nn import functional as F
    from ..nn import initializer as I
    base = name or _unique("multi_box_head")
    n_lvl = len(inputs)
    # replicate the kernel's prior-count logic to size the convs
    if min_sizes is None:
        ms, mx = [], []
        step_r = int(np.floor((max_ratio - min_ratio) / (n_lvl - 2)))
        for r in range(min_ratio, max_ratio + 1, step_r):
            ms.append(base_size * r / 100.0)
            mx.append(base_size * (r + step_r) / 100.0)
        ms = [base_size * 0.10] + ms
        mx = [base_size * 0.20] + mx
        min_sizes_l, max_sizes_l = ms[:n_lvl], mx[:n_lvl]
    else:
        min_sizes_l = list(min_sizes)
        max_sizes_l = list(max_sizes) if max_sizes else [None] * n_lvl
    loc_w, loc_b, conf_w, conf_b = [], [], [], []
    k = int(kernel_size)
    for i, feat in enumerate(inputs):
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                            (list, tuple)) \
            else [aspect_ratios[i]]
        n_prior = len(ar) * (2 if flip else 1) + 1
        if max_sizes_l[i]:
            n_prior += 1
        c_in = int(feat.shape[1])
        loc_w.append(_param(f"{base}.loc{i}.w_0",
                            (n_prior * 4, c_in, k, k), str(feat.dtype),
                            None, default_init=I.XavierNormal()))
        loc_b.append(_param(f"{base}.loc{i}.b_0", (n_prior * 4,),
                            str(feat.dtype), None, is_bias=True))
        conf_w.append(_param(f"{base}.conf{i}.w_0",
                             (n_prior * num_classes, c_in, k, k),
                             str(feat.dtype), None,
                             default_init=I.XavierNormal()))
        conf_b.append(_param(f"{base}.conf{i}.b_0",
                             (n_prior * num_classes,), str(feat.dtype),
                             None, is_bias=True))
    return F.multi_box_head(
        inputs, image, base_size, num_classes, aspect_ratios,
        min_ratio=min_ratio, max_ratio=max_ratio, min_sizes=min_sizes,
        max_sizes=max_sizes, steps=steps, step_w=step_w, step_h=step_h,
        offset=offset, variance=variance, flip=flip, clip=clip,
        kernel_size=kernel_size, pad=pad, stride=stride,
        min_max_aspect_ratios_order=min_max_aspect_ratios_order,
        loc_weights=loc_w, conf_weights=conf_w, loc_biases=loc_b,
        conf_biases=conf_b)


def py_func(func, x, out=None, backward_func=None,
            skip_vars_in_backward_input=None):
    from .compat import py_func as _pf
    return _pf(func, x, out=out, backward_func=backward_func,
               skip_vars_in_backward_input=skip_vars_in_backward_input)
