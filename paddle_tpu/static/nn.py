"""paddle.static.nn — data-dependent control flow over jax.lax.

Reference: python/paddle/static/nn/__init__.py:49-51 (cond/case/
while_loop/switch_case aliases) and fluid/layers/control_flow.py:2474
(case), :3591 (switch_case); the reference lowers these to
conditional_block / while ops inside a static Program. TPU-native
redesign: ONE implementation serves both execution modes —

- eager (concrete Tensor values): the predicate is read on the host and
  only the chosen branch runs, exactly like the reference's dygraph
  fallback. The autograd tape records the chosen branch's ops normally.
- traced (inside jit / to_static / Model steps): the predicate is a
  tracer, so the op lowers to jax.lax.cond/switch/while_loop — the
  branch becomes part of the compiled program and an exported model
  (jit.save) carries the data-dependent branch in its StableHLO, which
  the reference needs an AST rewrite (dygraph_to_static
  program_translator.py:756) to achieve.

Conversion boundary (documented limitation, mirrored from XLA's model):
traced while_loop bodies must keep loop-carried shapes/dtypes fixed;
Python-side effects inside branches run at trace time, not per-step; and
reverse-mode grad through a TRACED while_loop is unsupported (dynamic
trip count — jax raises; use a bounded lax.scan-style loop or eager
mode, where the host loop unrolls onto the tape and differentiates).
cond/case/switch_case differentiate fine in both modes.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, _is_tracer
from .nn_ops import *          # noqa: F401,F403  (fluid-style op layer)
from .nn_ops import __all__ as _ops_all

__all__ = ["cond", "case", "switch_case", "while_loop"] + list(_ops_all)


def _arr(x):
    return x._data if isinstance(x, Tensor) else x


def _is_traced(x) -> bool:
    return _is_tracer(_arr(x))


def _unwrap_tree(out):
    """Branch output (Tensor / nested list-tuple / None) -> jnp pytree."""
    return jax.tree_util.tree_map(
        lambda t: t._data if isinstance(t, Tensor) else jnp.asarray(t), out)


def _wrap_like(tree):
    return jax.tree_util.tree_map(Tensor, tree)


def _as_branch(fn: Callable):
    """Wrap a user branch (Tensors in closure, returns Tensors) as a
    zero-arg jnp-pytree function for lax."""

    def branch(_):
        return _unwrap_tree(fn())

    return branch


def cond(pred, true_fn=None, false_fn=None, name=None):
    """if/else on a boolean scalar (reference control_flow.py cond):
    runs only the taken branch eagerly; lowers to jax.lax.cond when
    traced. Both branches must return the same structure."""
    if true_fn is None or false_fn is None:
        raise TypeError("cond requires both true_fn and false_fn")
    p = _arr(pred)
    if not _is_traced(pred):
        return true_fn() if bool(np.asarray(jax.device_get(p)).reshape(())) \
            else false_fn()
    out = jax.lax.cond(jnp.reshape(p, ()).astype(bool),
                       _as_branch(true_fn), _as_branch(false_fn),
                       operand=None)
    return _wrap_like(out)


def case(pred_fn_pairs, default=None, name=None):
    """if/elif/.../else chain (reference control_flow.py:2474): first
    true pred wins; `default` (or the LAST fn when default is None) runs
    when nothing matches."""
    if not isinstance(pred_fn_pairs, (list, tuple)) or not pred_fn_pairs:
        raise TypeError("pred_fn_pairs must be a non-empty list/tuple")
    for pair in pred_fn_pairs:
        if not (isinstance(pair, (list, tuple)) and len(pair) == 2
                and callable(pair[1])):
            raise TypeError("each element must be a (pred, callable) pair")
    if default is None:
        default = pred_fn_pairs[-1][1]
    if not callable(default):
        raise TypeError("default must be callable")

    if not any(_is_traced(p) for p, _ in pred_fn_pairs):
        for p, fn in pred_fn_pairs:
            if bool(np.asarray(jax.device_get(_arr(p))).reshape(())):
                return fn()
        return default()

    # traced: right-fold into a nested lax.cond chain; the default is the
    # innermost branch so it only executes when every pred is false
    def chain(pairs):
        if not pairs:
            return _as_branch(default)
        (p, fn), rest = pairs[0], pairs[1:]
        return lambda _: jax.lax.cond(
            jnp.reshape(_arr(p), ()).astype(bool),
            _as_branch(fn), chain(rest), operand=None)

    return _wrap_like(chain(list(pred_fn_pairs))(None))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """C-style switch (reference control_flow.py:3591). branch_fns may be
    a dict {int: fn}, a list of fns, or a list of (int, fn) pairs; an
    unmatched index runs `default` (or the max-index fn when default is
    None)."""
    if isinstance(branch_fns, dict):
        keyed = dict(branch_fns)
    else:
        if not isinstance(branch_fns, (list, tuple)) or not branch_fns:
            raise TypeError("branch_fns must be a dict or non-empty list")
        if callable(branch_fns[0]):
            keyed = dict(enumerate(branch_fns))
        else:
            keyed = {}
            for pair in branch_fns:
                if not (isinstance(pair, (list, tuple)) and len(pair) == 2
                        and isinstance(pair[0], int)):
                    raise TypeError(
                        "branch_fns elements must be (int, callable)")
                if pair[0] in keyed:
                    raise ValueError(f"duplicate branch index {pair[0]}")
                keyed[pair[0]] = pair[1]
    for fn in keyed.values():
        if not callable(fn):
            raise TypeError("branch fns must be callable")
    if default is None:
        default = keyed[max(keyed)]
    if not callable(default):
        raise TypeError("default must be callable")

    idx = _arr(branch_index)
    if not _is_traced(branch_index):
        i = int(np.asarray(jax.device_get(idx)).reshape(()))
        return keyed.get(i, default)()

    # traced: dense branch table for lax.switch; gaps -> default. The
    # selector maps the runtime index to its table slot (unmatched -> 0,
    # the default slot).
    keys = sorted(keyed)
    table = [_as_branch(default)] + [_as_branch(keyed[k]) for k in keys]
    key_arr = jnp.asarray(keys, jnp.int32)
    i = jnp.reshape(idx, ()).astype(jnp.int32)
    matches = (key_arr == i)
    slot = jnp.where(matches.any(),
                     jnp.argmax(matches).astype(jnp.int32) + 1, 0)
    return _wrap_like(jax.lax.switch(slot, table, None))


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """while cond(vars): vars = body(vars) (reference while_loop).
    Eager: a host loop (only as many iterations as actually run).
    Traced: jax.lax.while_loop — loop-carried shapes must stay fixed.
    Returns the final loop_vars as a list."""
    if not callable(cond_fn) or not callable(body_fn):
        raise TypeError("cond_fn and body_fn must be callable")
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise TypeError("loop_vars must be a non-empty list/tuple")

    probe = cond_fn(*loop_vars)
    # the traced check must cover RAW jnp tracers too, not only Tensor
    # wrappers: a concrete initial predicate (e.g. a break-elimination
    # flag seeded False) over a traced carry still needs lax.while_loop
    if not _is_traced(probe) and not any(
            _is_traced(v) for v in loop_vars
            if isinstance(v, Tensor) or _is_tracer(v)):
        out = list(loop_vars)
        while bool(np.asarray(jax.device_get(_arr(cond_fn(*out)))).reshape(())):
            res = body_fn(*out)
            out = list(res) if isinstance(res, (list, tuple)) else [res]
        return out

    def cond_w(state):
        return jnp.reshape(_unwrap_tree(
            cond_fn(*_wrap_like(list(state)))), ()).astype(bool)

    def body_w(state):
        res = body_fn(*_wrap_like(list(state)))
        res = list(res) if isinstance(res, (list, tuple)) else [res]
        return tuple(_unwrap_tree(res))

    init = tuple(_unwrap_tree(list(loop_vars)))
    try:
        out = jax.lax.while_loop(cond_w, body_w, init)
    except TypeError:
        # carry-type mismatch, typically weak vs strong dtype: a python
        # scalar seed (`done = False`; `i = 0`) is weak-typed while the
        # body's output of the same var (e.g. a lax.cond result) is
        # strong. Re-seed the init from the body's output avals and pin
        # the body outputs to those dtypes so the carry is a fixed point.
        out_avals = jax.eval_shape(body_w, init)
        if tuple(np.shape(v) for v in init) != \
                tuple(a.shape for a in out_avals):
            raise           # genuine shape drift: not ours to paper over
        init = tuple(jax.lax.convert_element_type(v, a.dtype)
                     for v, a in zip(init, out_avals))

        def body_s(state):
            return tuple(jax.lax.convert_element_type(r, a.dtype)
                         for r, a in zip(body_w(state), out_avals))

        out = jax.lax.while_loop(cond_w, body_s, init)
    return list(_wrap_like(list(out)))
