"""paddle.static.amp — the reference re-exports the amp surface under
static (python/paddle/static/amp/__init__.py); one implementation
serves both paths here."""
from ..amp import *  # noqa: F401,F403
from ..amp import auto_cast, decorate, GradScaler  # noqa: F401

# reference layout: static.amp re-exports fluid.contrib.mixed_precision
# (+ its bf16 sub-package); one amp implementation serves every path
from .. import amp as mixed_precision  # noqa: E402,F401
from .. import amp as bf16  # noqa: E402,F401
