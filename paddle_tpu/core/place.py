"""Device identity (Place) over the PJRT device model.

TPU-native analog of /root/reference/paddle/fluid/platform/place.h
(CPUPlace/CUDAPlace/XPUPlace variant) and DeviceContextPool
(platform/device_context.h:695). On TPU there are no user-managed streams —
XLA owns scheduling — so a Place is just a typed handle to a jax.Device, and
the "context pool" is jax's device list.
"""
from __future__ import annotations

import functools

import jax


class Place:
    """Base device identity."""

    device_type: str = "unknown"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    @property
    def jax_device(self) -> jax.Device:
        devs = [d for d in jax.devices() if d.platform == self.device_type]
        if not devs:
            # Fall back to CPU host devices (always present).
            devs = jax.devices("cpu")
        return devs[self._device_id % len(devs)]

    def __eq__(self, other):
        return (type(self) is type(other)
                and self._device_id == other._device_id)

    def __hash__(self):
        return hash((type(self).__name__, self._device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self._device_id})"


class CPUPlace(Place):
    device_type = "cpu"

    def __init__(self):
        super().__init__(0)

    def __repr__(self):
        return "CPUPlace"


class TPUPlace(Place):
    """The native accelerator place of this framework (CUDAPlace analog)."""

    device_type = "tpu"


# Alias so code written against the reference API ("gpu:0") keeps working.
CUDAPlace = TPUPlace
XPUPlace = TPUPlace  # accelerator alias: the accelerator here IS the TPU


class TPUPinnedPlace(Place):
    """Host-pinned staging place (CUDAPinnedPlace analog). On PJRT, host
    staging buffers are managed by the runtime; this is an identity marker
    used by the DataLoader to request committed-host layouts."""

    device_type = "cpu"


CUDAPinnedPlace = TPUPinnedPlace


@functools.lru_cache(maxsize=None)
def _accelerator_platform():
    for d in jax.devices():
        if d.platform != "cpu":
            return d.platform
    return "cpu"


def is_compiled_with_tpu() -> bool:
    return _accelerator_platform() != "cpu"


# Parity alias (reference: paddle.is_compiled_with_cuda).
is_compiled_with_cuda = is_compiled_with_tpu


def get_device() -> str:
    p = _accelerator_platform()
    return "cpu" if p == "cpu" else f"{p}:0"


def device_count() -> int:
    return len(jax.devices())


def _place_to_jax_device(place):
    if place is None:
        return None
    if isinstance(place, Place):
        if isinstance(place, (TPUPlace,)) and place.device_type == "tpu":
            # Resolve against whatever accelerator platform is present.
            plat = _accelerator_platform()
            devs = jax.devices() if plat != "cpu" else jax.devices("cpu")
            return devs[place.get_device_id() % len(devs)]
        return place.jax_device
    if isinstance(place, jax.Device):
        return place
    raise TypeError(f"Expected Place or jax.Device, got {type(place)}")


def set_device(device: str):
    """paddle.set_device parity: 'cpu', 'tpu', 'tpu:0', 'gpu:0' (alias)."""
    global _default_place
    device = device.lower()
    if device == "cpu":
        _default_place = CPUPlace()
        return _default_place
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    if name in ("tpu", "gpu", "xpu", "axon"):
        _default_place = TPUPlace(idx)
        return _default_place
    raise ValueError(f"Unknown device {device!r}")


# Resolved LAZILY: probing devices at import would initialize the XLA
# backend and break jax.distributed.initialize (fleet.init on multi-host
# must run before any backend touch).
_default_place = None


def get_default_place() -> Place:
    global _default_place
    if _default_place is None:
        _default_place = TPUPlace(0) if is_compiled_with_tpu() else \
            CPUPlace()
    return _default_place
