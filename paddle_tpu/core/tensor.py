"""Eager Tensor + tape autograd on a functional substrate.

TPU-native redesign of the reference's imperative engine:
  * VarBase / VariableWrapper       -> Tensor (wraps an immutable jax.Array)
  * Tracer::TraceOp + GradOpMaker   -> `apply()` records a TapeNode holding the
    op's vjp closure obtained from jax.vjp at forward time
    (/root/reference/paddle/fluid/imperative/tracer.cc:132 created grad *descs*;
    here jax gives us the exact cotangent function directly)
  * BasicEngine (basic_engine.cc:39,:278) -> `backward()`: reverse-creation-order
    sweep over reachable TapeNodes with cotangent accumulation
    (gradient_accumulator.cc analog is a jnp add)
  * partial_grad_engine.cc          -> `grad()` in autograd.py
  * hooks.h                         -> Tensor.register_hook

Design note (why this is not a port): the reference needs per-op grad kernels
and a C++ engine because torch-style eager is its only fast path. Here eager
is the *debug/UX* path; the fast path is functional (`functional_call` +
jax.grad + jit), so the tape only has to be correct, not fast. Everything the
tape does is jax-traceable, so eager code also works inside `jax.jit`.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtype_mod
from . import place as place_mod
from .errors import InvalidArgumentError, enforce
from .flags import get_flags

# ---------------------------------------------------------------------------
# Grad mode (thread-local), paddle.no_grad parity.
# ---------------------------------------------------------------------------
_state = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)


class no_grad:
    """Context manager & decorator disabling tape recording."""

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        wrapper.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapper


class enable_grad(no_grad):
    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(True)
        return self


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------
_node_counter = [0]
_node_lock = threading.Lock()


class TapeNode:
    """One recorded op: holds the vjp closure, the primal closure (for
    higher-order grad via functional replay), and graph edges."""

    __slots__ = ("id", "vjp_fn", "call", "inputs", "out_avals", "n_outputs",
                 "tuple_out", "name")

    def __init__(self, vjp_fn, call, inputs, out_avals, name="", tuple_out=False):
        with _node_lock:
            _node_counter[0] += 1
            self.id = _node_counter[0]
        self.vjp_fn = vjp_fn
        self.call = call                # primal: (*diff_arrays) -> out
        self.inputs = inputs            # list[Tensor] — differentiable inputs
        self.out_avals = out_avals      # list[(shape, dtype)]
        self.n_outputs = len(out_avals)
        self.tuple_out = tuple_out
        self.name = name


_tensor_counter = [0]


def _next_name(prefix="tensor"):
    _tensor_counter[0] += 1
    return f"{prefix}_{_tensor_counter[0]}"


class Tensor:
    """Eager tensor: immutable jax.Array value + mutable framework metadata."""

    __slots__ = ("_data", "_stop_gradient", "grad", "_node", "_out_idx",
                 "name", "persistable", "_hooks", "_retain_grads", "trainable",
                 "__weakref__")

    def __init__(self, data, dtype=None, place=None, stop_gradient=True,
                 name=None, persistable=False):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, jax.Array) or dtype is not None:
            np_dtype = dtype_mod.convert_dtype(dtype)
            if isinstance(data, (bool, int)) and np_dtype is None:
                data = jnp.asarray(data)
            elif isinstance(data, float) and np_dtype is None:
                data = jnp.asarray(data, dtype_mod.get_default_dtype())
            else:
                if np_dtype is None:
                    # python lists / float64 numpy default to the framework
                    # default float dtype (paddle: to_tensor float data →
                    # get_default_dtype), not x64-inferred float64
                    if not isinstance(data, np.ndarray):
                        data = np.asarray(data)
                    if data.dtype == np.float64:
                        np_dtype = dtype_mod.get_default_dtype()
                data = jnp.asarray(data, np_dtype)
        dev = place_mod._place_to_jax_device(place)
        if dev is not None and not _is_tracer(data):
            data = jax.device_put(data, dev)
        self._data = data
        self._stop_gradient = bool(stop_gradient)
        self.grad = None
        self._node = None
        self._out_idx = 0
        self.name = name or _next_name()
        self.persistable = persistable
        self._hooks = []
        self._retain_grads = False
        self.trainable = True

    # -- value access -------------------------------------------------------
    @property
    def data(self):
        return self

    @property
    def value(self):
        return self._data

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        if _is_tracer(self._data):
            return place_mod.get_default_place()
        d = self._data.devices().pop()
        return place_mod.CPUPlace() if d.platform == "cpu" else place_mod.TPUPlace(d.id)

    @property
    def stop_gradient(self):
        return self._stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._stop_gradient = bool(v)

    def numpy(self):
        return np.asarray(self._data)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def astype(self, dtype):
        from .. import ops
        return ops.cast(self, dtype)

    cast = astype

    def clone(self):
        return apply(lambda x: x + 0, self)

    def detach(self):
        t = Tensor(self._data, stop_gradient=True, name=self.name + ".detach")
        return t

    def detach_(self):
        self._node = None
        self._stop_gradient = True
        return self

    def to(self, place=None, dtype=None):
        t = self
        if dtype is not None:
            t = t.astype(dtype)
        if place is not None:
            dev = place_mod._place_to_jax_device(place)
            t = Tensor(jax.device_put(t._data, dev), stop_gradient=t.stop_gradient)
        return t

    def cpu(self):
        return self.to(place_mod.CPUPlace())

    def tpu(self, idx=0):
        return self.to(place_mod.TPUPlace(idx))

    cuda = tpu

    def pin_memory(self):
        return self

    # -- mutation-looking API (framework metadata only; value swap) ---------
    def set_value(self, value):
        """In-place value replacement (parameters/optimizer use this)."""
        if isinstance(value, Tensor):
            value = value._data
        value = jnp.asarray(value, self._data.dtype)
        enforce(tuple(value.shape) == tuple(self._data.shape),
                f"set_value shape mismatch {value.shape} vs {self._data.shape}")
        self._data = value
        return self

    def copy_(self, other):
        return self.set_value(other)

    def fill_(self, v):
        self._data = jnp.full_like(self._data, v)
        return self

    def zero_(self):
        return self.fill_(0)

    def scale_(self, v):
        self._data = self._data * v
        return self

    # -- autograd -----------------------------------------------------------
    @property
    def is_leaf(self):
        return self._node is None

    def retain_grads(self):
        self._retain_grads = True

    def register_hook(self, hook: Callable):
        self._hooks.append(hook)

        class _Handle:
            def remove(_s):
                if hook in self._hooks:
                    self._hooks.remove(hook)

        return _Handle()

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def backward(self, grad_tensor=None, retain_graph=False):
        from .autograd import run_backward
        run_backward(self, grad_tensor, retain_graph)

    @property
    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    # -- python protocol ----------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        return bool(self._data)

    def __int__(self):
        return int(self._data)

    def __float__(self):
        return float(self._data)

    def __index__(self):
        return int(self._data)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return str(self)

    def __repr__(self):
        grad_s = "" if self._stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={dtype_mod.dtype_name(self.dtype)}"
                f"{grad_s},\n       {np.asarray(self._data) if not _is_tracer(self._data) else self._data!r})")

    def __getitem__(self, idx):
        idx = _convert_index(idx)
        return apply(lambda x: x[idx], self, op_name="slice")

    def __setitem__(self, idx, value):
        idx = _convert_index(idx)
        if isinstance(value, Tensor):
            value = value._data
        self._data = self._data.at[idx].set(value)

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, *a, **k):
        return self._data.__dlpack__(*a, **k)

    # dim/rank parity helpers
    def dim(self):
        return self.ndim

    def rank(self):
        return self.ndim

    def numel(self):
        return self.size

    def element_size(self):
        return np.dtype(self.dtype).itemsize

    # arithmetic dunders are attached by ops._bind to avoid circular imports


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def _convert_index(idx):
    def conv(i):
        if isinstance(i, Tensor):
            return i._data
        return i
    if isinstance(idx, tuple):
        return tuple(conv(i) for i in idx)
    return conv(idx)


def _differentiable(t: Tensor) -> bool:
    return (not t._stop_gradient
            and jnp.issubdtype(t.dtype, jnp.inexact))


# ---------------------------------------------------------------------------
# Op dispatch: the Tracer::TraceOp analog.
# ---------------------------------------------------------------------------
_amp_hook = [None]  # paddle_tpu.amp installs maybe_cast_inputs here
_profiler_hook = [None]  # paddle_tpu.profiler installs its per-op hook


def apply(fn, *args, op_name: str = None, n_outputs: int = None, **kwargs):
    """Run `fn` on raw arrays, wrapping outputs as Tensors and recording a
    TapeNode when grad is required.

    `fn` is called as fn(*raw_args, **kwargs) where Tensor args are replaced
    by their jax.Array payloads. Differentiation is w.r.t. inexact-dtype
    Tensor args with stop_gradient=False.
    """
    if _profiler_hook[0] is not None:  # per-op RecordEvent while profiling
        rec = _profiler_hook[0](op_name or getattr(fn, "__name__", "op"))
        if rec is not None:
            with rec:
                return _apply_inner(fn, args, op_name, kwargs)
    return _apply_inner(fn, args, op_name, kwargs)


def _apply_inner(fn, args, op_name, kwargs):
    raw = [a._data if isinstance(a, Tensor) else a for a in args]
    if _amp_hook[0] is not None:  # autocast (set by paddle_tpu.amp on import)
        raw = _amp_hook[0](op_name or getattr(fn, "__name__", "op"), raw)
    diff_pos = [i for i, a in enumerate(args)
                if isinstance(a, Tensor) and _differentiable(a)] \
        if is_grad_enabled() else []

    if not diff_pos:
        out = fn(*raw, **kwargs)
        # jax-native passthrough: called on raw tracers with no Tensor in
        # sight (user's own jit/grad around a paddle op) — hand back raw
        # arrays so the op is a valid JAX function, not a Tensor factory
        if (not any(isinstance(a, Tensor) for a in args)
                and any(isinstance(a, jax.core.Tracer) for a in args)):
            return out
        return _wrap_outputs(out, None)

    def call(*diff_arrays):
        full = list(raw)
        for p, arr in zip(diff_pos, diff_arrays):
            full[p] = arr
        return fn(*full, **kwargs)

    out, vjp_fn = jax.vjp(call, *[raw[p] for p in diff_pos])

    leaves = out if isinstance(out, (tuple, list)) else (out,)
    out_avals = [(tuple(l.shape), l.dtype) for l in leaves]
    node = TapeNode(vjp_fn, call, [args[p] for p in diff_pos], out_avals,
                    name=op_name or getattr(fn, "__name__", "op"),
                    tuple_out=isinstance(out, (tuple, list)))
    result = _wrap_outputs(out, node)

    if get_flags("check_nan_inf"):
        _check_nan_inf(result, node.name)
    return result


def _wrap_outputs(out, node):
    if isinstance(out, (tuple, list)):
        ts = []
        for i, leaf in enumerate(out):
            t = Tensor(leaf, stop_gradient=(node is None))
            t._node = node
            t._out_idx = i
            ts.append(t)
        return tuple(ts)
    t = Tensor(out, stop_gradient=(node is None))
    t._node = node
    t._out_idx = 0
    return t


def _check_nan_inf(result, name):
    ts = result if isinstance(result, tuple) else (result,)
    for t in ts:
        if _is_tracer(t._data):
            return
        if jnp.issubdtype(t.dtype, jnp.inexact) and not bool(jnp.isfinite(t._data).all()):
            raise FloatingPointError(
                f"NaN/Inf detected in output of op '{name}' "
                f"(FLAGS_check_nan_inf, nan_inf_utils_detail analog)")


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
