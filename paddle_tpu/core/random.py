"""Seeded RNG built on jax's splittable PRNG.

TPU-native analog of /root/reference/paddle/fluid/framework/generator.cc and
pybind/generator_py.cc (global + per-device generators). The reference keeps
stateful Philox generators per device; on TPU the idiomatic design is a
*splittable functional* key — we keep a small stateful wrapper so eager code
gets fresh randomness per call (dygraph parity) while jitted code threads keys
explicitly (`split_key`).
"""
from __future__ import annotations

import threading

import jax
import numpy as np


class Generator:
    """Stateful wrapper over a jax PRNG key chain."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._key = jax.random.key(self._seed)
        self._offset = 0
        return self

    @property
    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        """Return a fresh key; advances internal state (eager use only)."""
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            self._offset += 1
            return sub

    def get_state(self):
        return {"seed": self._seed, "offset": self._offset}

    def set_state(self, state):
        self.manual_seed(state["seed"])
        # Replay the chain to the recorded offset.
        for _ in range(state["offset"]):
            self._key, _ = jax.random.split(self._key)
        self._offset = state["offset"]


_default_generator = Generator(np.random.randint(0, 2**31 - 1))


def seed(s: int):
    """paddle.seed parity: reseed the global generator (and numpy for loaders)."""
    _default_generator.manual_seed(s)
    np.random.seed(s % (2**32))
    return _default_generator


def default_generator() -> Generator:
    return _default_generator


def next_key():
    return _default_generator.next_key()


def split_key(key, num: int = 2):
    return jax.random.split(key, num)


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)
