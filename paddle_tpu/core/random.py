"""Seeded RNG built on jax's splittable PRNG.

TPU-native analog of /root/reference/paddle/fluid/framework/generator.cc and
pybind/generator_py.cc (global + per-device generators). The reference keeps
stateful Philox generators per device; on TPU the idiomatic design is a
*splittable functional* key — we keep a small stateful wrapper so eager code
gets fresh randomness per call (dygraph parity) while jitted code threads keys
explicitly (`split_key`).
"""
from __future__ import annotations

import threading

import jax
import numpy as np


class Generator:
    """Stateful wrapper over a jax PRNG key chain."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._key = None        # materialised lazily: creating a key at
        self._offset = 0        # import would initialise the XLA backend
        self._replay = 0
        return self

    def _ensure_key(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
            for _ in range(getattr(self, "_replay", 0)):
                self._key, _ = jax.random.split(self._key)
            self._replay = 0

    @property
    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        """Return a fresh key; advances internal state (eager use only)."""
        with self._lock:
            self._ensure_key()
            self._key, sub = jax.random.split(self._key)
            self._offset += 1
            return sub

    def get_state(self):
        return {"seed": self._seed, "offset": self._offset}

    def set_state(self, state):
        # record only; the chain replays inside _ensure_key so restoring a
        # checkpoint before fleet.init keeps the backend untouched
        self.manual_seed(state["seed"])
        self._offset = state["offset"]
        self._replay = state["offset"]


_default_generator = Generator(np.random.randint(0, 2**31 - 1))

# Functional key scope: inside jit-traced code (functional_call / train step)
# randomness must derive from an explicit traced key, not the eager global
# generator (which would bake a constant into the compiled program). A scope
# holds a mutable key cell that next_key() splits from while active.
_scope = threading.local()


class key_scope:
    """`with key_scope(step_key): ...` — eager random ops inside draw
    deterministic splits of `step_key` (thread each step's key explicitly)."""

    def __init__(self, key):
        self._cell = [key]

    def __enter__(self):
        stack = getattr(_scope, "stack", None)
        if stack is None:
            stack = _scope.stack = []
        stack.append(self._cell)
        return self

    def __exit__(self, *exc):
        _scope.stack.pop()
        return False


def seed(s: int):
    """paddle.seed parity: reseed the global generator (and numpy for loaders)."""
    _default_generator.manual_seed(s)
    np.random.seed(s % (2**32))
    return _default_generator


def default_generator() -> Generator:
    return _default_generator


def next_key():
    stack = getattr(_scope, "stack", None)
    if stack:
        cell = stack[-1]
        cell[0], sub = jax.random.split(cell[0])
        return sub
    return _default_generator.next_key()


def split_key(key, num: int = 2):
    return jax.random.split(key, num)


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)
