"""Ragged-sequence utilities — the LoDTensor capability, TPU-shaped.

Reference: LoDTensor (framework/lod_tensor.h:114) carries level-of-detail
offsets so one dense buffer holds variable-length sequences, and
sequence ops consume the offsets directly.

TPU-native design decision: XLA requires static shapes, so ragged data
lives as (padded dense tensor, lengths) — the form every jitted op can
consume — and LoD offsets become a host-side descriptor used at the data
boundary. This module converts between the three forms and provides the
mask/segment helpers the reference's sequence ops derive from LoD:

    pack_sequence   [list of [Ti, ...]] -> (padded [B, Tmax, ...], lengths)
    unpack_sequence (padded, lengths)   -> list of [Ti, ...]
    lod_from_lengths / lengths_from_lod   offsets <-> lengths
    sequence_mask   lengths -> bool [B, Tmax] (traceable)
    segment_ids     lengths -> flat segment ids for segment reductions
"""
from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp
import numpy as np

__all__ = ["pack_sequence", "unpack_sequence", "lod_from_lengths",
           "lengths_from_lod", "sequence_mask", "segment_ids"]


def lod_from_lengths(lengths: Sequence[int]) -> List[int]:
    """[3, 1, 2] -> [0, 3, 4, 6] (reference level-0 offsets)."""
    out = [0]
    for n in lengths:
        out.append(out[-1] + int(n))
    return out


def lengths_from_lod(lod: Sequence[int]) -> List[int]:
    return [int(b) - int(a) for a, b in zip(lod[:-1], lod[1:])]


def pack_sequence(seqs, pad_value=0, max_len=None):
    """List of per-sequence arrays [Ti, ...] -> (padded [B, Tmax, ...]
    numpy array, lengths int64 [B]). The static-shape form XLA wants."""
    seqs = [np.asarray(s) for s in seqs]
    lengths = np.array([s.shape[0] for s in seqs], np.int64)
    Tmax = int(max_len if max_len is not None
               else (lengths.max() if len(seqs) else 0))
    trailing = seqs[0].shape[1:] if seqs else ()
    out = np.full((len(seqs), Tmax) + trailing, pad_value,
                  seqs[0].dtype if seqs else np.float32)
    for i, s in enumerate(seqs):
        t = min(s.shape[0], Tmax)
        out[i, :t] = s[:t]
    return out, lengths


def unpack_sequence(padded, lengths):
    padded = np.asarray(padded)
    return [padded[i, :int(n)] for i, n in enumerate(np.asarray(lengths))]


def sequence_mask(lengths, max_len=None, dtype="bool"):
    """lengths [B] -> mask [B, Tmax]; True on valid positions (reference
    sequence_mask op). Traceable under jit ONLY with an explicit max_len
    (shapes must be static); without it, lengths must be concrete."""
    lengths = jnp.asarray(lengths)
    if max_len is None:
        import jax as _jax
        if isinstance(lengths, _jax.core.Tracer):
            raise ValueError(
                "sequence_mask under jit needs an explicit max_len "
                "(output shape must be static)")
        max_len = int(np.asarray(lengths).max())
    pos = jnp.arange(int(max_len))
    mask = pos[None, :] < lengths[:, None]
    return mask if dtype == "bool" else mask.astype(dtype)


def segment_ids(lengths, total=None):
    """lengths [B] -> flat ids (0,0,0,1,2,2,...) for segment_sum-style
    reductions over ragged flat layouts. With `total`, the result is
    padded to that static length using segment id B (out of range, so
    segment_sum(num_segments=B) drops the padding) or truncated."""
    lengths = np.asarray(lengths)
    ids = np.repeat(np.arange(len(lengths)), lengths)
    if total is not None:
        if len(ids) > total:
            ids = ids[:total]
        else:
            ids = np.concatenate(
                [ids, np.full(total - len(ids), len(lengths), ids.dtype)])
    return ids
