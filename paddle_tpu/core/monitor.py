"""Stat registry + device memory counters.

Reference: StatRegistry (platform/monitor.h:77 — global named int
counters, e.g. STAT_GPU_MEM) exported to python via
global_value_getter_setter.cc.

TPU-native: the registry keeps the reference's named-counter surface for
framework/user instrumentation; device memory numbers come from PJRT
(jax Device.memory_stats) instead of allocator internals, because XLA
owns HBM on TPU (SURVEY.md rows 7/10).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import jax

__all__ = ["stat_inc", "stat_set", "stat_get", "stat_reset", "all_stats",
           "device_memory_stats", "hbm_usage"]

_lock = threading.Lock()
_stats: Dict[str, int] = {}


def stat_inc(name: str, value: int = 1) -> int:
    with _lock:
        _stats[name] = _stats.get(name, 0) + int(value)
        return _stats[name]


def stat_set(name: str, value: int):
    with _lock:
        _stats[name] = int(value)


def stat_get(name: str, default: int = 0) -> int:
    with _lock:
        return _stats.get(name, default)


def stat_reset(name: Optional[str] = None):
    with _lock:
        if name is None:
            _stats.clear()
        else:
            _stats.pop(name, None)


def all_stats() -> Dict[str, int]:
    with _lock:
        return dict(_stats)


def device_memory_stats(device=None) -> Dict[str, int]:
    """PJRT per-device memory counters (bytes_in_use, peak_bytes_in_use,
    bytes_limit where the runtime reports them)."""
    device = device or jax.devices()[0]
    try:
        return dict(device.memory_stats() or {})
    except Exception:
        return {}


def hbm_usage(device=None):
    """(bytes_in_use, bytes_limit) — the STAT_GPU_MEM analog for HBM."""
    st = device_memory_stats(device)
    return st.get("bytes_in_use", 0), st.get("bytes_limit", 0)
