"""Stat registry + device memory counters.

Reference: StatRegistry (platform/monitor.h:77 — global named int
counters, e.g. STAT_GPU_MEM) exported to python via
global_value_getter_setter.cc.

TPU-native: the named-counter surface is kept (stat_inc/stat_set/...)
but the backing store is the observability metrics registry — every
stat lands as a ``paddle_tpu_monitor_stat{name="..."}`` gauge sample,
so framework/user instrumentation shows up on the same ``/metrics``
scrape as the serving counters (docs/observability.md). Device memory
numbers come from PJRT (jax ``Device.memory_stats``) instead of
allocator internals, because XLA owns HBM on TPU (SURVEY.md rows 7/10);
every probe is hardened to return empty/zero — never raise — when the
backend is unreachable or reports no memory stats (CPU).
"""
from __future__ import annotations

from typing import Dict, Optional

from ..observability import metrics as _metrics

__all__ = ["stat_inc", "stat_set", "stat_get", "stat_reset", "all_stats",
           "device_memory_stats", "all_device_memory_stats", "hbm_usage"]

_STATS = _metrics.gauge(
    "paddle_tpu_monitor_stat",
    "Named framework counters (StatRegistry parity surface: "
    "core.monitor.stat_inc/stat_set).",
    labelnames=("name",))


def stat_inc(name: str, value: int = 1) -> int:
    return int(_STATS.labels(name=str(name)).inc(int(value)))


def stat_set(name: str, value: int):
    _STATS.labels(name=str(name)).set(int(value))


def stat_get(name: str, default: int = 0) -> int:
    v = _STATS.value(name=str(name))
    return default if v is None else int(v)


def stat_reset(name: Optional[str] = None):
    if name is None:
        _STATS.clear()
    else:
        _STATS.remove(name=str(name))


def all_stats() -> Dict[str, int]:
    return {labels["name"]: int(child.get())
            for labels, child in _STATS.samples()}


def device_memory_stats(device=None) -> Dict[str, int]:
    """PJRT per-device memory counters (bytes_in_use, peak_bytes_in_use,
    bytes_limit where the runtime reports them). Returns ``{}`` — never
    raises — when the backend fails to initialize or the device reports
    no memory stats (CPU)."""
    try:
        if device is None:
            import jax
            devs = jax.devices()
            if not devs:
                return {}
            device = devs[0]
        return dict(device.memory_stats() or {})
    except Exception:
        return {}


def all_device_memory_stats() -> Dict[str, Dict[str, int]]:
    """{str(device): memory_stats} over every visible device; devices
    (or backends) that cannot report come back as empty dicts."""
    try:
        import jax
        devs = jax.devices()
    except Exception:
        return {}
    out = {}
    for d in devs:
        try:
            out[str(d)] = dict(d.memory_stats() or {})
        except Exception:
            out[str(d)] = {}
    return out


def hbm_usage(device=None):
    """(bytes_in_use, bytes_limit) — the STAT_GPU_MEM analog for HBM.
    (0, 0) when the runtime has nothing to report."""
    st = device_memory_stats(device)
    return st.get("bytes_in_use", 0), st.get("bytes_limit", 0)
