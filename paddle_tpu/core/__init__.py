from . import dtype, errors, flags, place, random
from .autograd import grad
from .tensor import (Tensor, apply, enable_grad, is_grad_enabled, no_grad,
                     set_grad_enabled, to_tensor)
