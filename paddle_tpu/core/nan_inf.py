"""Jit-path NaN/Inf scanning.

Reference: FLAGS_check_nan_inf (platform/flags.cc:44) scans every op
output post-run (framework/details/nan_inf_utils_detail.cc). The eager
dispatcher has that per-op scan (core/tensor.py); under jit the graph
executes as one XLA program, so the TPU-native equivalent is a fused
finite-check over a whole pytree (typically the gradient tree) with ONE
device reduction, raising host-side with the offending leaf names —
per-op checks inside jit would break fusion and serialize the step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .flags import get_flags

__all__ = ["tree_finite", "guard_tree"]


def _leaves_with_names(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    names, leaves = [], []
    for path, leaf in flat:
        names.append(jax.tree_util.keystr(path))
        leaves.append(leaf)
    return names, leaves


def tree_finite(tree):
    """(all_finite scalar, per-leaf finite vector) — traceable."""
    _, leaves = _leaves_with_names(tree)
    flags = jnp.stack([jnp.isfinite(l).all()
                       if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
                       else jnp.asarray(True) for l in leaves])
    return flags.all(), flags


def guard_tree(tree, label="gradients"):
    """Identity on `tree`; when FLAGS_check_nan_inf is set, attaches a
    fused finite-check that raises FloatingPointError on the host with
    the first offending leaf names. Safe inside jit.

    The flag is read at TRACE time: set it before the first call of a
    jitted step (compiled programs bake the decision in — toggling later
    requires recompilation, unlike the per-op eager check)."""
    if not get_flags("check_nan_inf"):
        return tree
    names, _ = _leaves_with_names(tree)
    _, flags = tree_finite(tree)

    def report(mask):
        import numpy as np
        bad = [n for n, ok in zip(names, np.asarray(mask)) if not ok]
        if bad:
            raise FloatingPointError(
                f"NaN/Inf detected in {label}: {bad[:10]}"
                + (f" (+{len(bad) - 10} more)" if len(bad) > 10 else ""))

    jax.debug.callback(report, flags)
    return tree
