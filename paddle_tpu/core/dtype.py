"""Dtype registry.

TPU-native analog of the reference's VarType dtype enum
(/root/reference/paddle/fluid/framework/framework.proto:106) and the numeric
types in /root/reference/paddle/fluid/platform/{float16,bfloat16,complex64}.h.
On TPU, bfloat16 is the first-class reduced precision type; float16 exists for
API parity but bf16 is preferred throughout (MXU native).
"""
from __future__ import annotations

import jax

# Paddle's dtype surface includes int64/float64 tensors (int64 is the default
# index/label dtype). Enable x64 so those dtypes are real; JAX weak typing
# keeps python-scalar arithmetic at float32, and the framework's creation /
# division paths pin the default float dtype explicitly, so the hot path
# stays f32/bf16 (TPU has no f64 MXU). This is process-global: applications
# embedding plain JAX code alongside paddle_tpu can opt out with
# PADDLE_TPU_NO_X64=1 (int64/float64 tensors then degrade to int32/float32).
from . import flags as _flags

if not _flags.env_value("PADDLE_TPU_NO_X64"):
    jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects are jax/numpy dtypes; we expose paddle-style names.
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR2DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "fp16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "fp64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_FLOATING = {float16, bfloat16, float32, float64}
_INTEGRAL = {uint8, int8, int16, int32, int64}
_COMPLEX = {complex64, complex128}


def convert_dtype(dtype) -> jnp.dtype:
    """Normalize a user-provided dtype (str, np dtype, jnp dtype) to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _STR2DTYPE:
            raise ValueError(f"Unknown dtype string: {dtype!r}")
        return jnp.dtype(_STR2DTYPE[dtype])
    return jnp.dtype(dtype)


def dtype_name(dtype) -> str:
    return np.dtype(dtype).name


def is_floating_point(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)


def is_complex(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating)


# Global default dtype (paddle.get_default_dtype / set_default_dtype parity).
_default_dtype = jnp.float32


def set_default_dtype(d):
    global _default_dtype
    d = convert_dtype(d)
    if not is_floating_point(d):
        raise TypeError(f"default dtype must be floating point, got {d}")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype
