"""Backward engines over the eager tape.

`run_backward`  — BasicEngine analog (/root/reference/paddle/fluid/imperative/
                  basic_engine.cc:39 Init, :278 Execute): seeds the root
                  cotangent, sweeps reachable TapeNodes in reverse creation
                  order (a valid topological order for an eager tape), calls
                  each node's vjp, accumulates into leaf .grad.
`grad`          — partial_grad_engine.cc analog (paddle.grad API): cotangents
                  for selected inputs only, optional create_graph.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from .errors import InvalidArgumentError, enforce
from .tensor import TapeNode, Tensor, apply, is_grad_enabled, no_grad


def _collect_nodes(roots: Sequence[Tensor]):
    """All TapeNodes reachable from roots, sorted by descending creation id."""
    seen = {}
    stack = [t._node for t in roots if t._node is not None]
    while stack:
        n = stack.pop()
        if n.id in seen:
            continue
        seen[n.id] = n
        for inp in n.inputs:
            if inp._node is not None and inp._node.id not in seen:
                stack.append(inp._node)
    return sorted(seen.values(), key=lambda n: -n.id)


def _accum(store: dict, key, value):
    prev = store.get(key)
    store[key] = value if prev is None else prev + value


def _run_hooks(t: Tensor, g):
    for hook in t._hooks:
        out = hook(Tensor(g, stop_gradient=True))
        if out is not None:
            g = out._data if isinstance(out, Tensor) else jnp.asarray(out)
    return g


def _sweep(roots, root_grads, retain_graph, wanted=None, accumulate_leaf=True):
    """Shared reverse sweep. Returns {id(tensor): cotangent} for `wanted`."""
    nodes = _collect_nodes(roots)
    # cotangents keyed per (node_id, out_idx) for intermediates and id(tensor)
    # for requested/leaf tensors.
    node_cots = {}      # (node_id, out_idx) -> array
    tensor_cots = {}    # id(tensor) -> array (wanted/leaf results)
    wanted_ids = {id(t) for t in (wanted or [])}
    # Map (node_id, out_idx) -> live output tensors (for retain_grads/hooks).
    out_tensors = {}
    for t in _live_outputs(roots, nodes):
        out_tensors.setdefault((t._node.id, t._out_idx), []).append(t)

    for root, g in zip(roots, root_grads):
        if root._node is None:
            if id(root) in wanted_ids:
                _accum(tensor_cots, id(root), g)
            elif accumulate_leaf and not root.stop_gradient:
                _leaf_accum(root, g)
        else:
            _accum(node_cots, (root._node.id, root._out_idx), g)

    for node in nodes:
        cots = []
        has_any = False
        for i, (shape, dt) in enumerate(node.out_avals):
            c = node_cots.pop((node.id, i), None)
            if c is None:
                c = jnp.zeros(shape, dt)
            else:
                has_any = True
                for t in out_tensors.get((node.id, i), []):
                    c = _run_hooks(t, c)
                    if t._retain_grads:
                        _leaf_accum(t, c)
                if c.dtype != dt:
                    # mixed-precision graphs (AMP) hand back cotangents in
                    # the downstream op's compute dtype; jax.vjp requires
                    # the exact output aval
                    c = c.astype(dt)
            cots.append(c)
        if not has_any:
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                "Trying to backward through the graph a second time; "
                "specify retain_graph=True on the first backward call.")
        seed = tuple(cots) if node.tuple_out else cots[0]
        in_cots = node.vjp_fn(seed)
        if not retain_graph:
            node.vjp_fn = None
        for inp, g in zip(node.inputs, in_cots):
            if isinstance(g, jax.Array) and g.dtype == jax.dtypes.float0:
                continue
            if inp._node is not None:
                _accum(node_cots, (inp._node.id, inp._out_idx), g)
            else:
                g = _run_hooks(inp, g)
                if id(inp) in wanted_ids:
                    _accum(tensor_cots, id(inp), g)
                if accumulate_leaf and not inp.stop_gradient:
                    _leaf_accum(inp, g)
            if inp._node is None and id(inp) not in wanted_ids and inp.stop_gradient:
                continue
    return tensor_cots


def _leaf_accum(t: Tensor, g):
    if t.grad is None:
        t.grad = Tensor(g, stop_gradient=True, name=t.name + "@GRAD")
    else:
        t.grad.set_value(t.grad._data + g)


def _live_outputs(roots, nodes):
    """Tensors we know about that are outputs of reachable nodes: the roots
    plus all node inputs (covers hook/retain_grads on intermediates that are
    themselves inputs to later ops — the common case)."""
    out = [t for t in roots if t._node is not None]
    for n in nodes:
        for inp in n.inputs:
            if inp._node is not None:
                out.append(inp)
    return out


def run_backward(root: Tensor, grad_tensor=None, retain_graph=False):
    enforce(root._node is not None or not root.stop_gradient,
            "Tensor has no grad graph (stop_gradient=True and no history)",
            InvalidArgumentError)
    if grad_tensor is None:
        g = jnp.ones(root._data.shape, root._data.dtype)
    else:
        g = grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)
    with no_grad():
        _sweep([root], [g], retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad parity (partial_grad_engine.cc analog).

    create_graph=True is implemented functionally: we re-trace through
    jax.vjp of a replayed closure is not available on an eager tape, so we
    instead run the sweep *with grad recording enabled*, which records the
    vjp computations themselves onto the tape (double-backward works because
    every vjp is built from jax ops executed through `apply`-free raw jnp —
    so for create_graph we wrap cotangent math in Tensors).
    """
    outputs = _as_list(outputs)
    inputs = _as_list(inputs)
    if retain_graph is None:
        retain_graph = create_graph
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    seeds = []
    for o, go in zip(outputs, grad_outputs):
        if go is None:
            seeds.append(jnp.ones(o._data.shape, o._data.dtype))
        else:
            seeds.append(go._data if isinstance(go, Tensor) else jnp.asarray(go))

    if create_graph:
        # Functional double-backward: build a pure function of the inputs and
        # use jax.vjp so the returned grads carry a fresh tape.
        return _grad_create_graph(outputs, inputs, seeds, allow_unused)

    with no_grad():
        cots = _sweep(outputs, seeds, retain_graph, wanted=inputs,
                      accumulate_leaf=False)
    results = []
    for t in inputs:
        c = cots.get(id(t))
        if c is None:
            if not allow_unused:
                raise InvalidArgumentError(
                    f"Input tensor {t.name} is unused in the graph "
                    "(pass allow_unused=True to get None)")
            results.append(None)
        else:
            results.append(Tensor(c, stop_gradient=True))
    return results


def _grad_create_graph(outputs, inputs, seeds, allow_unused):
    """Higher-order grad via functional replay of the recorded tape region.

    The tape alone cannot express d(grad)/d(input) because jax.vjp hides the
    input dependence inside its closure. Instead we reconstruct the pure
    function F(inputs) -> outputs from the stored primal closures (node.call)
    and differentiate it with jax.vjp *through `apply`*, so the returned
    gradients carry a fresh tape and support further .backward()/grad().
    """
    from .tensor import apply as _apply

    nodes = _collect_nodes(outputs)
    fwd_nodes = list(reversed(nodes))          # ascending id = forward order
    input_pos = {id(t): i for i, t in enumerate(inputs)}

    # Usedness check (paddle raises on structurally-unused inputs).
    used = set()
    for n in fwd_nodes:
        for inp in n.inputs:
            if id(inp) in input_pos:
                used.add(id(inp))
    for o in outputs:
        if id(o) in input_pos:
            used.add(id(o))
    if not allow_unused:
        for t in inputs:
            if id(t) not in used:
                raise InvalidArgumentError(
                    f"Input tensor {t.name} is unused in the graph "
                    "(pass allow_unused=True to get None)")

    def replay(*in_arrays):
        env = {}

        def val(t):
            if id(t) in input_pos:
                return in_arrays[input_pos[id(t)]]
            if t._node is not None and (t._node.id, t._out_idx) in env:
                return env[(t._node.id, t._out_idx)]
            return t._data

        for n in fwd_nodes:
            out = n.call(*[val(i) for i in n.inputs])
            leaves = out if isinstance(out, (tuple, list)) else (out,)
            for i, leaf in enumerate(leaves):
                env[(n.id, i)] = leaf
        return tuple(val(o) for o in outputs)

    def pullback(*in_arrays):
        _, vjp_fn = jax.vjp(replay, *in_arrays)
        return tuple(vjp_fn(tuple(seeds)))

    grads = _apply(pullback, *inputs, op_name="grad")
    if not isinstance(grads, tuple):
        grads = (grads,)
    return [g if id(t) in used else None
            for t, g in zip(inputs, grads)]


def _as_list(x):
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]
