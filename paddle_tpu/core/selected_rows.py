"""SelectedRows: the sparse row-subset gradient representation.

Reference: framework/selected_rows.h:41 — a (rows, value, height) triple
carrying only the embedding rows an op actually touched; the reference's
sparse-grad path keeps lookup_table gradients in this form so optimizers
and the parameter server update rows instead of the full table.

TPU-native split: ON-CHIP embedding backward stays a dense scatter-add —
that is what the MXU/XLA execute efficiently and what the tape produces.
SelectedRows is the HOST-SIDE interchange format: extracting the touched
rows from a dense grad (from_dense) for parameter-server push_sparse,
row-wise optimizer updates on host tables, and compact checkpoint deltas.
`Embedding(sparse=True)` records the ids of the last forward so the
touched-row set is known without scanning the dense grad for nonzeros.
"""
from __future__ import annotations

import numpy as np

__all__ = ["SelectedRows"]


class SelectedRows:
    """rows [n] int64, value [n, ...] — rows index dim 0 of a [height, ...]
    dense tensor. Duplicate rows are allowed until consolidated."""

    def __init__(self, rows, value, height):
        self.rows = np.asarray(rows, np.int64).ravel()
        self.value = np.asarray(value)
        self.height = int(height)
        if self.value.shape[0] != self.rows.shape[0]:
            raise ValueError(
                f"rows ({self.rows.shape[0]}) and value rows "
                f"({self.value.shape[0]}) disagree")

    @classmethod
    def from_dense(cls, dense_grad, ids=None):
        """Extract the sparse form from a dense gradient. With `ids` (the
        forward's lookup indices) only those rows are gathered; otherwise
        nonzero rows are detected."""
        dense = np.asarray(dense_grad)
        if ids is not None:
            rows = np.unique(np.asarray(ids).ravel())
        else:
            nz = np.abs(dense).reshape(dense.shape[0], -1).sum(axis=1)
            rows = np.nonzero(nz)[0]
        return cls(rows, dense[rows], dense.shape[0])

    def merge_rows(self):
        """Consolidate duplicate rows by summation (reference
        MergeAdd functor for SelectedRows)."""
        uniq, inv = np.unique(self.rows, return_inverse=True)
        out = np.zeros((uniq.shape[0],) + self.value.shape[1:],
                       self.value.dtype)
        np.add.at(out, inv, self.value)
        return SelectedRows(uniq, out, self.height)

    def to_dense(self):
        out = np.zeros((self.height,) + self.value.shape[1:],
                       self.value.dtype)
        np.add.at(out, self.rows, self.value)
        return out

    def apply_sgd(self, param, lr):
        """Row-wise SGD on a host-side numpy table (in place)."""
        m = self.merge_rows()
        param[m.rows] -= lr * m.value
        return param

    def push_to_ps(self, client, table: int, lr: float = 1.0):
        """One push_sparse RPC carrying only the touched rows
        (distributed/ps PSClient)."""
        m = self.merge_rows()
        client.push_sparse(table, m.rows.astype(np.uint64),
                           m.value.astype(np.float32), lr=lr)

    def __repr__(self):
        return (f"SelectedRows(rows={self.rows.shape[0]}, "
                f"height={self.height}, dim={self.value.shape[1:]})")
