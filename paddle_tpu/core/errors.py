"""Enforce-style error machinery.

TPU-native analog of PADDLE_ENFORCE* and the error-code taxonomy in
/root/reference/paddle/fluid/platform/{enforce.h,errors.h,error_codes.proto}.
Python-level because the hot path on TPU is compiled by XLA — shape/type
validation happens at trace time, where Python exceptions are idiomatic.
"""
from __future__ import annotations


class EnforceNotMet(RuntimeError):
    """Base error, mirrors platform::EnforceNotMet."""

    code = "LEGACY"


class InvalidArgumentError(EnforceNotMet, ValueError):
    code = "INVALID_ARGUMENT"


class NotFoundError(EnforceNotMet, KeyError):
    code = "NOT_FOUND"


class OutOfRangeError(EnforceNotMet, IndexError):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(EnforceNotMet):
    code = "ALREADY_EXISTS"


class ResourceExhaustedError(EnforceNotMet, MemoryError):
    code = "RESOURCE_EXHAUSTED"


class PreconditionNotMetError(EnforceNotMet):
    code = "PRECONDITION_NOT_MET"


class PermissionDeniedError(EnforceNotMet, PermissionError):
    code = "PERMISSION_DENIED"


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    code = "EXECUTION_TIMEOUT"


class UnimplementedError(EnforceNotMet, NotImplementedError):
    code = "UNIMPLEMENTED"


class UnavailableError(EnforceNotMet):
    code = "UNAVAILABLE"


class FatalError(EnforceNotMet):
    code = "FATAL"


class ExternalError(EnforceNotMet):
    code = "EXTERNAL"


def enforce(cond, msg="", exc=InvalidArgumentError):
    """PADDLE_ENFORCE equivalent: raise `exc` with `msg` when cond is falsy."""
    if not cond:
        raise exc(msg)


def enforce_eq(a, b, msg="", exc=InvalidArgumentError):
    if a != b:
        raise exc(f"{msg} (expected {a!r} == {b!r})")


def enforce_gt(a, b, msg="", exc=InvalidArgumentError):
    if not a > b:
        raise exc(f"{msg} (expected {a!r} > {b!r})")


def enforce_ge(a, b, msg="", exc=InvalidArgumentError):
    if not a >= b:
        raise exc(f"{msg} (expected {a!r} >= {b!r})")
