"""Global flag registry.

TPU-native analog of the gflags tier in
/root/reference/paddle/fluid/platform/flags.cc (33 DEFINE sites) and the
python getter/setter bridge /root/reference/paddle/fluid/pybind/
global_value_getter_setter.cc. A single-process registry: flags are defined
with a default + doc, overridable from the environment (FLAGS_xxx) and from
`set_flags`, read with `get_flags`.

XLA-level knobs are forwarded by appending to XLA_FLAGS before first device
use; everything else is framework-local.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from .errors import NotFoundError


@dataclass
class _Flag:
    name: str
    default: Any
    doc: str
    parser: Callable[[str], Any]
    value: Any = None
    on_change: Optional[Callable[[Any], None]] = None


_REGISTRY: Dict[str, _Flag] = {}
_LOCK = threading.Lock()


def _parse_bool(s):
    if isinstance(s, bool):
        return s
    return str(s).lower() in ("1", "true", "yes", "on")


def define_flag(name, default, doc="", parser=None, on_change=None):
    """DEFINE_xxx equivalent. Environment FLAGS_<name> overrides the default."""
    if parser is None:
        if isinstance(default, bool):
            parser = _parse_bool
        elif isinstance(default, int):
            parser = int
        elif isinstance(default, float):
            parser = float
        else:
            parser = str
    value = default
    env = os.environ.get("FLAGS_" + name)
    if env is not None:
        value = parser(env)
    with _LOCK:
        _REGISTRY[name] = _Flag(name, default, doc, parser, value, on_change)
    return value


def get_flags(flags):
    """paddle.get_flags parity: accepts a name or list of names."""
    single = isinstance(flags, str)
    names = [flags] if single else list(flags)
    out = {}
    for n in names:
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _REGISTRY:
            raise NotFoundError(f"Flag {n!r} is not defined")
        out[n] = _REGISTRY[key].value
    return out[flags] if single else out


def set_flags(flags: Dict[str, Any]):
    """paddle.set_flags parity."""
    for n, v in flags.items():
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _REGISTRY:
            raise NotFoundError(f"Flag {n!r} is not defined")
        f = _REGISTRY[key]
        f.value = f.parser(v) if isinstance(v, str) else v
        if f.on_change is not None:
            f.on_change(f.value)


def all_flags():
    return {n: f.value for n, f in _REGISTRY.items()}


# ---------------------------------------------------------------------------
# Framework flags (the subset of platform/flags.cc that is meaningful on TPU,
# plus TPU-specific ones).
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False,
            "Scan op outputs for NaN/Inf in eager mode (flags.cc:44 analog).")
define_flag("benchmark", False, "Sync + time every eager op.")
define_flag("eager_delete_tensor_gb", 0.0,
            "Kept for API parity; XLA owns buffer lifetime on TPU.")
define_flag("allocator_strategy", "xla",
            "Kept for API parity; PJRT/XLA own device memory on TPU.")
define_flag("fraction_of_gpu_memory_to_use", 0.92,
            "Parity alias for per-chip HBM headroom fraction.")
define_flag("use_pallas_attention", True,
            "Use the Pallas flash-attention kernel when applicable.")
define_flag("pallas_attention_min_seq", 512,
            "Route sdpa to the flash kernel only at seq_len >= this; below "
            "it XLA's fused composition wins on-chip (measured crossover: "
            "at T=1024 flash is 3.9 ms vs 4.6 ms XLA per GPT-2 layer "
            "fwd+bwd on v5e, and the gap widens with T^2 above).")
define_flag("amp_dtype", "bfloat16",
            "Reduced precision dtype for AMP (bf16 is MXU native).")
define_flag("cudnn_deterministic", False,
            "Parity alias: forces deterministic reductions where we control them.")
define_flag("max_inplace_grad_add", 0,
            "Parity flag from flags.cc; unused (functional grads).")
define_flag("tpu_matmul_precision", "default",
            "jax.lax matmul precision: default|high|highest.")
define_flag("xla_latency_hiding_scheduler", True,
            "Forward --xla_tpu_enable_latency_hiding_scheduler so XLA "
            "schedules collectives/HBM copies under compute (comm/compute "
            "overlap). Applied by forward_xla_flags() on TPU targets only.")
define_flag("xla_async_collectives", True,
            "Forward the async-collective-fusion trio so the dp gradient "
            "all-reduce runs asynchronously and overlaps the backward. "
            "Applied by forward_xla_flags() on TPU targets only.")


# ---------------------------------------------------------------------------
# XLA_FLAGS forwarding (comm/compute overlap knobs)
# ---------------------------------------------------------------------------
# The production-TPU scheduling flags (MaxText's standard set). XLA reads
# XLA_FLAGS once at backend init, so forwarding must happen before first
# device use — paddle_tpu/__init__ calls forward_xla_flags() at import.
_XLA_OVERLAP_FLAGS = {
    "xla_latency_hiding_scheduler": (
        "--xla_tpu_enable_latency_hiding_scheduler",
    ),
    "xla_async_collectives": (
        "--xla_tpu_enable_async_collective_fusion",
        "--xla_tpu_enable_async_collective_fusion_fuse_all_gather",
        "--xla_tpu_enable_async_collective_fusion_multiple_steps",
        "--xla_tpu_overlap_compute_collective_tc",
    ),
}


def _xla_overlap_opts():
    out = []
    for opts in _XLA_OVERLAP_FLAGS.values():
        out.extend(opts)
    return out


def forward_xla_flags(force=False):
    """Append the enabled comm/compute-overlap knobs to XLA_FLAGS.

    CAUTION: XLA aborts the process (LOG(FATAL) in parse_flags_from_env)
    on flags its build does not register, and --xla_tpu_* flags only
    exist in libtpu-backed builds. So forwarding is gated:

    - ``PADDLE_TPU_XLA_OVERLAP=0/off``: never forward.
    - ``PADDLE_TPU_XLA_OVERLAP=1/on`` (or ``force=True``): forward unless
      the process targets CPU.
    - default (auto): forward only when JAX_PLATFORMS explicitly names
      ``tpu`` — the one target where these flags are known-registered.

    Flags the user already set in XLA_FLAGS (either polarity) are left
    alone. Returns the list of options appended (empty when gated off).
    """
    mode = os.environ.get("PADDLE_TPU_XLA_OVERLAP", "auto").strip().lower()
    if mode in ("0", "off", "false", "no"):
        return []
    plats = os.environ.get("JAX_PLATFORMS", "").lower()
    if plats.split(",")[0].strip() == "cpu":
        return []
    if not (force or mode in ("1", "on", "true", "yes")):
        if "tpu" not in plats:
            return []
    current = os.environ.get("XLA_FLAGS", "")
    added = []
    for flag_name, opts in _XLA_OVERLAP_FLAGS.items():
        if not get_flags(flag_name):
            continue
        for opt in opts:
            if opt in current:
                continue
            added.append(f"{opt}=true")
    if added:
        os.environ["XLA_FLAGS"] = (current + " " + " ".join(added)).strip()
    return added


def strip_xla_overlap_flags(env=None):
    """Remove every overlap knob from XLA_FLAGS (in `env` or os.environ).

    Used by fallback paths that re-target a CPU backend after a TPU
    failure: the CPU build would abort on the unknown --xla_tpu_* flags
    this module (or the user) forwarded."""
    env = os.environ if env is None else env
    current = env.get("XLA_FLAGS", "")
    if not current:
        return env
    kept = [tok for tok in current.split()
            if tok.split("=")[0] not in _xla_overlap_opts()]
    if kept:
        env["XLA_FLAGS"] = " ".join(kept)
    else:
        env.pop("XLA_FLAGS", None)
    return env
