"""paddle.regularizer — L1Decay / L2Decay.

Reference: python/paddle/regularizer.py:20 (L1Decay), :82 (L2Decay); the
reference appends a decay op to each parameter's gradient in the
append_regularization_ops pass (fluid/regularizer.py). TPU-native: the
optimizer folds the decay term into the gradient at update time —
L2Decay as coeff * param and L1Decay as coeff * sign(param), both added
to the gradient (grad-side, so the decay also reaches optimizers whose
own weight_decay is decoupled, e.g. AdamW/Lamb).

Resolution order matches the reference: a ParamAttr(regularizer=...) on
the parameter overrides the optimizer-wide weight_decay regularizer
(fluid/regularizer.py append_regularization_ops: "The Regularizer
specified in Parameter has higher priority").
"""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    _l1 = False

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L1Decay(WeightDecayRegularizer):
    """loss += coeff * sum(|param|)  ->  grad += coeff * sign(param)."""

    _l1 = True


class L2Decay(WeightDecayRegularizer):
    """loss += 0.5 * coeff * sum(param^2)  ->  grad += coeff * param."""

    _l1 = False
