"""DataLoader (reference: python/paddle/fluid/reader.py:149 DataLoader,
dataloader/dataloader_iter.py:100 single-process, :230 multi-process with
shared-memory LoDTensors via mmap_allocator.cc).

TPU-native design: workers produce *numpy host batches*; the device transfer
happens once per batch (jax.device_put, or sharded put in the fit loop) —
there is no per-tensor CUDA pinned-memory dance because PJRT owns staging.
Multi-process mode uses the native shared-memory ring queue
(native/shm_queue.cpp) when built, else multiprocessing.queues; worker death
is detected via sentinels + process liveness polling (the SIGCHLD +
CleanupFuncRegistrar analog in fluid/multiprocess_utils.py).
"""
from __future__ import annotations

import atexit
import itertools
import multiprocessing as mp
import queue
import threading
import traceback
from typing import Callable, Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler, SequenceSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    """Stack a list of samples into batched numpy arrays (reference:
    fluid/dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([s.numpy() for s in batch])
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return type(sample)(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return batch
    return np.asarray(batch)


def _to_tensor_tree(obj, return_list):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_tensor_tree(v, return_list) for v in obj)
    if isinstance(obj, dict):
        return {k: _to_tensor_tree(v, return_list) for k, v in obj.items()}
    return obj


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, use_shared_memory=True,
                 prefetch_factor=2, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.use_shared_memory = use_shared_memory
        self.prefetch_factor = prefetch_factor
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._iterable_dataset = isinstance(dataset, IterableDataset)
        self._as_tensor = True

        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif self._iterable_dataset:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            self.batch_size = batch_size
            if batch_size is None:
                self.batch_sampler = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset=dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)

    def __len__(self):
        if self._iterable_dataset:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def __iter__(self):
        if self.num_workers == 0:
            return self._single_process_iter()
        return _MultiprocessIter(self)

    def __call__(self):
        return self.__iter__()

    def _single_process_iter(self):
        if self._iterable_dataset:
            def gen():
                batch = []
                for sample in self.dataset:
                    batch.append(sample)
                    if len(batch) == self.batch_size:
                        yield _to_tensor_tree(self.collate_fn(batch),
                                              self.return_list)
                        batch = []
                if batch and not getattr(self, "drop_last", False):
                    yield _to_tensor_tree(self.collate_fn(batch),
                                          self.return_list)
            return gen()

        if self.batch_sampler is None:  # batch_size=None: sample = batch
            def gen():
                for i in range(len(self.dataset)):
                    yield _to_tensor_tree(self.dataset[i], self.return_list)
            return gen()

        def gen():
            for indices in self.batch_sampler:
                batch = [self.dataset[i] for i in indices]
                yield _to_tensor_tree(self.collate_fn(batch), self.return_list)
        return gen()


def _worker_loop(dataset, index_queue, out_queue, collate_fn, init_fn,
                 worker_id, num_workers, iterable, batch_size, drop_last,
                 base_seed):
    """Reference: fluid/dataloader/worker.py:171 _worker_loop."""
    try:
        np.random.seed((base_seed + worker_id) % (2 ** 32))
        _worker_info.info = WorkerInfo(worker_id, num_workers, dataset,
                                       base_seed)
        if init_fn is not None:
            init_fn(worker_id)
        if iterable:
            it = iter(dataset)
            batch = []
            for sample in it:
                batch.append(sample)
                if len(batch) == batch_size:
                    out_queue.put((0, collate_fn(batch)))
                    batch = []
            if batch and not drop_last:
                out_queue.put((0, collate_fn(batch)))
            out_queue.put((None, None))  # exhausted
            return
        while True:
            task = index_queue.get()
            if task is None:
                break
            seq, indices = task
            try:
                batch = [dataset[i] for i in indices]
                out_queue.put((seq, collate_fn(batch)))
            except Exception:
                out_queue.put((seq, _WorkerException(traceback.format_exc())))
    except KeyboardInterrupt:
        pass


class _WorkerException:
    def __init__(self, tb):
        self.tb = tb


class _MultiprocessIter:
    """Reference: dataloader_iter.py:230 _DataLoaderIterMultiProcess —
    N workers pull index batches from per-worker queues; a collector thread
    reorders completed batches by sequence id."""

    def __init__(self, loader: DataLoader):
        self.loader = loader
        self._ctx = mp.get_context("fork")
        n = loader.num_workers
        self._index_queues = [self._ctx.Queue() for _ in range(n)]
        self._out_queue = self._ctx.Queue()
        self._workers = []
        self._seq_send = 0
        self._seq_rcvd = 0
        self._cache = {}
        self._exhausted_workers = 0
        base_seed = np.random.randint(0, 2 ** 31 - 1)
        iterable = loader._iterable_dataset

        for wid in range(n):
            w = self._ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, self._index_queues[wid],
                      self._out_queue, loader.collate_fn,
                      loader.worker_init_fn, wid, n, iterable,
                      loader.batch_size,
                      getattr(loader, "drop_last", False), base_seed),
                daemon=True)
            w.start()
            self._workers.append(w)
        atexit.register(self._shutdown)

        if not iterable:
            self._sampler_iter = iter(loader.batch_sampler)
            # prime the pipeline
            for _ in range(n * loader.prefetch_factor):
                self._dispatch_next()

    def _dispatch_next(self):
        try:
            indices = next(self._sampler_iter)
        except StopIteration:
            return False
        wid = self._seq_send % len(self._workers)
        self._index_queues[wid].put((self._seq_send, indices))
        self._seq_send += 1
        return True

    def __iter__(self):
        return self

    def __next__(self):
        loader = self.loader
        if loader._iterable_dataset:
            while True:
                if self._exhausted_workers == len(self._workers):
                    self._shutdown()
                    raise StopIteration
                seq, data = self._get_from_queue()
                if seq is None:
                    self._exhausted_workers += 1
                    continue
                return _to_tensor_tree(data, loader.return_list)

        if self._seq_rcvd >= self._seq_send and not self._dispatch_next():
            self._shutdown()
            raise StopIteration
        while self._seq_rcvd not in self._cache:
            seq, data = self._get_from_queue()
            self._cache[seq] = data
        data = self._cache.pop(self._seq_rcvd)
        self._seq_rcvd += 1
        self._dispatch_next()
        if isinstance(data, _WorkerException):
            self._shutdown()
            raise RuntimeError("DataLoader worker failed:\n" + data.tb)
        return _to_tensor_tree(data, loader.return_list)

    def _get_from_queue(self):
        timeout = self.loader.timeout or 5.0
        while True:
            try:
                return self._out_queue.get(timeout=timeout)
            except queue.Empty:
                dead = [w for w in self._workers if not w.is_alive()]
                if dead and self._exhausted_workers < len(dead):
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader {len(dead)} worker(s) died unexpectedly "
                        "(watch_local_trainers analog)") from None
                if self.loader.timeout:
                    self._shutdown()
                    raise RuntimeError("DataLoader timed out") from None

    def _shutdown(self):
        for q in getattr(self, "_index_queues", []):
            try:
                q.put(None)
            except Exception:
                pass
        for w in getattr(self, "_workers", []):
            if w.is_alive():
                w.terminate()
        self._workers = []
