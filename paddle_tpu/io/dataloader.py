"""DataLoader (reference: python/paddle/fluid/reader.py:149 DataLoader,
dataloader/dataloader_iter.py:100 single-process, :230 multi-process with
shared-memory LoDTensors via mmap_allocator.cc).

TPU-native design: workers produce *numpy host batches*; the device transfer
happens once per batch (jax.device_put, or sharded put in the fit loop) —
there is no per-tensor CUDA pinned-memory dance because PJRT owns staging.

Multi-process mode uses the SPAWN start method (fork under JAX's
multithreaded runtime risks deadlock — the reference forks because its C++
runtime is fork-aware; ours is not) and a shared-memory batch transport:
each collated batch's arrays are packed into ONE posix shm segment
(multiprocessing.shared_memory = the mmap_allocator.cc capability; the
packing itself is memcpy-bound so numpy already runs it at memory speed)
and only (shapes, dtypes, offsets, shm name) travel through the queue.
Worker death is detected via sentinels + liveness polling (SIGCHLD +
CleanupFuncRegistrar analog in fluid/multiprocess_utils.py).
"""
from __future__ import annotations

import atexit
import itertools
import multiprocessing as mp
import queue
import threading
import traceback
from multiprocessing import shared_memory as shm_mod
from typing import Callable, Optional

import numpy as np

from ..core.flags import define_flag, get_flags
from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler, SequenceSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info",
           "device_prefetch"]

define_flag("dataloader_start_method", "spawn",
            "multiprocessing start method for DataLoader workers; spawn "
            "avoids the fork-under-threads deadlock the JAX runtime "
            "documents, fork trades safety for startup latency.")

_worker_info = threading.local()


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    """Stack a list of samples into batched numpy arrays (reference:
    fluid/dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([s.numpy() for s in batch])
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return type(sample)(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return batch
    return np.asarray(batch)


def _tree_map(fn, obj):
    """Map fn over non-container leaves of a list/tuple/dict tree (the one
    traversal shared by collate, shm pack/unpack and prefetch)."""
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_map(fn, v) for v in obj)
    if isinstance(obj, dict):
        return {k: _tree_map(fn, v) for k, v in obj.items()}
    return fn(obj)


def _to_tensor_tree(obj, return_list):
    return _tree_map(
        lambda v: Tensor(v) if isinstance(v, np.ndarray) else v, obj)


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, use_shared_memory=True,
                 prefetch_factor=2, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.use_shared_memory = use_shared_memory
        self.prefetch_factor = prefetch_factor
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = bool(persistent_workers)
        self._persistent_iter = None
        self._iterable_dataset = isinstance(dataset, IterableDataset)
        self._as_tensor = True

        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif self._iterable_dataset:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            self.batch_size = batch_size
            if batch_size is None:
                self.batch_sampler = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset=dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)

    def __len__(self):
        if self._iterable_dataset:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def __iter__(self):
        if self.num_workers == 0:
            return self._single_process_iter()
        if self.persistent_workers and not self._iterable_dataset:
            # amortize spawn startup across epochs (reference keeps worker
            # processes alive the same way)
            if (self._persistent_iter is None
                    or not self._persistent_iter._workers):
                self._persistent_iter = _MultiprocessIter(
                    self, persistent=True)
            else:
                self._persistent_iter.reset()
            return self._persistent_iter
        return _MultiprocessIter(self)

    def __call__(self):
        return self.__iter__()

    def _single_process_iter(self):
        if self._iterable_dataset:
            def gen():
                batch = []
                for sample in self.dataset:
                    batch.append(sample)
                    if len(batch) == self.batch_size:
                        yield _to_tensor_tree(self.collate_fn(batch),
                                              self.return_list)
                        batch = []
                if batch and not getattr(self, "drop_last", False):
                    yield _to_tensor_tree(self.collate_fn(batch),
                                          self.return_list)
            return gen()

        if self.batch_sampler is None:  # batch_size=None: sample = batch
            def gen():
                for i in range(len(self.dataset)):
                    yield _to_tensor_tree(self.dataset[i], self.return_list)
            return gen()

        def gen():
            for indices in self.batch_sampler:
                batch = [self.dataset[i] for i in indices]
                yield _to_tensor_tree(self.collate_fn(batch), self.return_list)
        return gen()


# ---------------------------------------------------------------------------
# shared-memory batch transport (mmap_allocator.cc capability)
# ---------------------------------------------------------------------------

class _ShmBatch:
    """Marker travelling through the queue: arrays live in one shm segment,
    only layout metadata is pickled."""

    def __init__(self, shm_name, layout):
        self.shm_name = shm_name
        self.layout = layout       # pickled tree with _ArrRef leaves


class _ArrRef:
    def __init__(self, offset, shape, dtype):
        self.offset = offset
        self.shape = shape
        self.dtype = dtype


def _tree_arrays(obj):
    """Collect ndarray leaves without rebuilding containers."""
    out = []

    def visit(o):
        if isinstance(o, np.ndarray):
            out.append(o)
        elif isinstance(o, (list, tuple)):
            for v in o:
                visit(v)
        elif isinstance(o, dict):
            for v in o.values():
                visit(v)

    visit(obj)
    return out


def _pack_batch(data):
    """Collated tree -> (_ShmBatch, shm segment). The CONSUMER unlinks the
    segment; the producer unregisters it from its resource_tracker so the
    worker's exit cleanup does not double-unlink."""
    arrays = [a for a in _tree_arrays(data) if not a.dtype.hasobject]
    total = sum(int(a.nbytes) for a in arrays)
    if total == 0:
        return data, None
    seg = shm_mod.SharedMemory(create=True, size=max(total, 1))
    try:  # consumer owns the name from here on
        from multiprocessing import resource_tracker
        resource_tracker.unregister(seg._name, "shared_memory")
    except Exception:
        pass
    offset = 0

    def pack_leaf(obj):
        nonlocal offset
        if not isinstance(obj, np.ndarray) or obj.dtype.hasobject:
            # PyObject pointers cannot cross processes through raw bytes;
            # non-array and object-dtype leaves ride mp.Queue's pickling
            return obj
        a = np.ascontiguousarray(obj)
        view = np.ndarray(a.shape, a.dtype, buffer=seg.buf, offset=offset)
        view[...] = a
        ref = _ArrRef(offset, a.shape, str(a.dtype))
        offset += int(a.nbytes)
        return ref

    layout = _tree_map(pack_leaf, data)
    return _ShmBatch(seg.name, layout), seg


def _unpack_batch(msg: "_ShmBatch"):
    seg = shm_mod.SharedMemory(name=msg.shm_name)
    try:
        def unpack_leaf(obj):
            if isinstance(obj, _ArrRef):
                view = np.ndarray(obj.shape, obj.dtype, buffer=seg.buf,
                                  offset=obj.offset)
                return view.copy()     # detach before the segment dies
            return obj

        return _tree_map(unpack_leaf, msg.layout)
    finally:
        seg.close()
        try:
            seg.unlink()
        except FileNotFoundError:
            pass


def _worker_loop(dataset, index_queue, out_queue, collate_fn, init_fn,
                 worker_id, num_workers, iterable, batch_size, drop_last,
                 base_seed, use_shm):
    """Reference: fluid/dataloader/worker.py:171 _worker_loop."""
    def put(seq, data):
        if use_shm and not isinstance(data, _WorkerException):
            msg, seg = _pack_batch(data)
            if seg is not None:
                seg.close()   # segment persists until the consumer unlinks
            out_queue.put((seq, msg))
        else:
            out_queue.put((seq, data))

    try:
        np.random.seed((base_seed + worker_id) % (2 ** 32))
        _worker_info.info = WorkerInfo(worker_id, num_workers, dataset,
                                       base_seed)
        if init_fn is not None:
            init_fn(worker_id)
        if iterable:
            it = iter(dataset)
            batch = []
            for sample in it:
                batch.append(sample)
                if len(batch) == batch_size:
                    put(0, collate_fn(batch))
                    batch = []
            if batch and not drop_last:
                put(0, collate_fn(batch))
            out_queue.put((None, None))  # exhausted
            return
        while True:
            task = index_queue.get()
            if task is None:
                break
            seq, indices = task
            try:
                batch = [dataset[i] for i in indices]
                put(seq, collate_fn(batch))
            except Exception:
                out_queue.put((seq, _WorkerException(traceback.format_exc())))
    except KeyboardInterrupt:
        pass


class _WorkerException:
    def __init__(self, tb):
        self.tb = tb


class _MultiprocessIter:
    """Reference: dataloader_iter.py:230 _DataLoaderIterMultiProcess —
    N workers pull index batches from per-worker queues; a collector thread
    reorders completed batches by sequence id."""

    def __init__(self, loader: DataLoader, persistent=False):
        self.loader = loader
        self._persistent = persistent
        self._ctx = mp.get_context(get_flags("dataloader_start_method"))
        n = loader.num_workers
        self._index_queues = [self._ctx.Queue() for _ in range(n)]
        self._out_queue = self._ctx.Queue()
        self._workers = []
        self._seq_send = 0
        self._seq_rcvd = 0
        self._cache = {}
        self._exhausted_workers = 0
        base_seed = np.random.randint(0, 2 ** 31 - 1)
        iterable = loader._iterable_dataset

        for wid in range(n):
            w = self._ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, self._index_queues[wid],
                      self._out_queue, loader.collate_fn,
                      loader.worker_init_fn, wid, n, iterable,
                      loader.batch_size,
                      getattr(loader, "drop_last", False), base_seed,
                      loader.use_shared_memory),
                daemon=True)
            w.start()
            self._workers.append(w)
        atexit.register(self._shutdown)

        if not iterable:
            self._sampler_iter = iter(loader.batch_sampler)
            # prime the pipeline
            for _ in range(n * loader.prefetch_factor):
                self._dispatch_next()

    def _dispatch_next(self):
        try:
            indices = next(self._sampler_iter)
        except StopIteration:
            return False
        wid = self._seq_send % len(self._workers)
        self._index_queues[wid].put((self._seq_send, indices))
        self._seq_send += 1
        return True

    def __iter__(self):
        return self

    def __next__(self):
        loader = self.loader
        if loader._iterable_dataset:
            while True:
                if self._exhausted_workers == len(self._workers):
                    self._shutdown()
                    raise StopIteration
                seq, data = self._get_from_queue()
                if seq is None:
                    self._exhausted_workers += 1
                    continue
                return _to_tensor_tree(data, loader.return_list)

        if self._seq_rcvd >= self._seq_send and not self._dispatch_next():
            if not self._persistent:
                self._shutdown()
            raise StopIteration
        while self._seq_rcvd not in self._cache:
            seq, data = self._get_from_queue()
            self._cache[seq] = data
        data = self._cache.pop(self._seq_rcvd)
        self._seq_rcvd += 1
        self._dispatch_next()
        if isinstance(data, _WorkerException):
            self._shutdown()
            raise RuntimeError("DataLoader worker failed:\n" + data.tb)
        return _to_tensor_tree(data, loader.return_list)

    def reset(self):
        """Re-arm a persistent iterator for the next epoch: drain any
        abandoned in-flight batches (unlinking their shm), restart the
        sampler, re-prime the pipeline."""
        while self._seq_rcvd < self._seq_send:
            if self._seq_rcvd in self._cache:
                self._cache.pop(self._seq_rcvd)
            else:
                seq, _ = self._get_from_queue()
                if seq != self._seq_rcvd:
                    self._cache[seq] = None
                    continue
            self._seq_rcvd += 1
        self._cache.clear()
        self._sampler_iter = iter(self.loader.batch_sampler)
        for _ in range(len(self._workers) * self.loader.prefetch_factor):
            self._dispatch_next()

    def _get_from_queue(self):
        timeout = self.loader.timeout or 5.0
        while True:
            try:
                seq, data = self._out_queue.get(timeout=timeout)
                if isinstance(data, _ShmBatch):
                    data = _unpack_batch(data)
                return seq, data
            except queue.Empty:
                dead = [w for w in self._workers if not w.is_alive()]
                if dead and self._exhausted_workers < len(dead):
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader {len(dead)} worker(s) died unexpectedly "
                        "(watch_local_trainers analog)") from None
                if self.loader.timeout:
                    self._shutdown()
                    raise RuntimeError("DataLoader timed out") from None

    def _shutdown(self):
        for q in getattr(self, "_index_queues", []):
            try:
                q.put(None)
            except Exception:
                pass
        # stop producers FIRST, then sweep in-flight shm segments — a
        # drain-before-terminate races with workers still packing batches
        for w in getattr(self, "_workers", []):
            if w.is_alive():
                w.terminate()
        for w in getattr(self, "_workers", []):
            try:
                w.join(timeout=2.0)
            except Exception:
                pass
        try:
            while True:
                _, data = self._out_queue.get(timeout=0.05)
                if isinstance(data, _ShmBatch):
                    try:
                        seg = shm_mod.SharedMemory(name=data.shm_name)
                        seg.close()
                        seg.unlink()
                    except FileNotFoundError:
                        pass
        except Exception:
            pass
        self._workers = []


def device_prefetch(iterator, sharding=None, depth=2, place=None):
    """Overlap host->device transfer with compute: a background thread
    device_puts upcoming batches (double buffering by default). Reference
    capability: operators/reader/buffered_reader.cc (device-buffered
    queue feeding the executor).

        for xb, yb in io.device_prefetch(loader, sharding=data_sharding):
            step(xb, yb)

    `place` (optional) overrides the per-leaf placement: a callable
    `array -> device array` applied to each batch leaf on the feeder
    thread. The fleet step path passes `CompiledTrainStep.put_batch` so
    host-side preproc (pipeline microbatching) AND the sharded
    device_put both happen off the critical path; `step()` then detects
    already-placed arrays and skips the per-step transfer."""
    import jax

    def _fit_sharding(x):
        """Truncate a NamedSharding's spec to the array's rank (a [B]
        per-sample tensor under a ('dp','sp') batch spec takes P('dp') —
        same rule as the compiled step's _put_data)."""
        from jax.sharding import NamedSharding, PartitionSpec
        if isinstance(sharding, NamedSharding) and \
                len(sharding.spec) > x.ndim:
            return NamedSharding(sharding.mesh,
                                 PartitionSpec(*sharding.spec[:x.ndim]))
        return sharding

    def put(tree):
        def one(x):
            if isinstance(x, Tensor):
                x = x._data
            if place is not None:
                try:
                    return place(x)
                except Exception:
                    return x          # step() re-places on its own path
            if isinstance(x, np.ndarray):
                return jax.device_put(x, _fit_sharding(x))
            return x
        return _tree_map(one, tree)

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    done = object()
    stop = threading.Event()

    def offer(item):
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def feeder():
        try:
            for item in iterator:
                if not offer(put(item)):
                    return            # consumer abandoned the stream
        except BaseException as e:    # propagate to the consumer
            offer(e)
            return
        offer(done)

    t = threading.Thread(target=feeder, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is done:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()                    # unblock the feeder on early exit
