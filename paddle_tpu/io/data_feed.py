"""PS-path data ingestion: slot-parsed in-memory dataset.

Reference: the DataFeed/Dataset family feeding PS and Dataset trainers —
MultiSlotDataFeed text parsing (framework/data_feed.h:664, parse loop
data_feed.cc), InMemoryDataset with load/shuffle
(framework/data_set.h:157; python fleet/dataset/) — SURVEY.md §2 row 45.

Wire format (MultiSlot text): one sample per line; for each declared slot
in order: `<n> v1 ... vn` (n = number of values). Sparse slots hold
uint64 feature ids of varying length per sample (the LoD raggedness);
dense slots hold exactly `dim` floats.

    words = Slot("words", dtype="uint64")            # sparse, ragged
    label = Slot("label", dtype="float32", dim=1)    # dense
    ds = InMemoryDataset([words, label])
    ds.load_from_files([path1, path2])   # or ds.add_samples(lines)
    ds.local_shuffle(seed=0)
    for batch in ds.batches(batch_size=32):
        batch["words"]   -> (values [total], lod offsets [B+1])
        batch["label"]   -> np.ndarray [B, 1]

Batches hand sparse slots over as (flat values, LoD offsets) — the
SelectedRows/LoD representation the PS embedding path consumes
(core/lod.py helpers turn them into padded/masked arrays for the model).
"""
from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Dict, Iterator, List, Sequence

import numpy as np

__all__ = ["Slot", "InMemoryDataset", "QueueDataset",
           "parse_multi_slot_line"]


@dataclass
class Slot:
    name: str
    dtype: str = "uint64"     # "uint64" (sparse ids) | "float32" (dense)
    dim: int = 0              # >0: dense slot with fixed width

    @property
    def is_sparse(self) -> bool:
        return self.dim == 0


def parse_multi_slot_line(line: str, slots: Sequence[Slot]):
    """One text line -> [per-slot value list] (MultiSlotDataFeed parser)."""
    toks = line.split()
    out = []
    i = 0
    for slot in slots:
        if i >= len(toks):
            raise ValueError(f"line ran out of tokens at slot {slot.name!r}")
        n = int(toks[i])
        i += 1
        vals = toks[i:i + n]
        if len(vals) != n:
            raise ValueError(
                f"slot {slot.name!r} declares {n} values, found {len(vals)}")
        i += n
        if slot.is_sparse:
            out.append(np.asarray(vals, np.uint64))
        else:
            if n != slot.dim:
                raise ValueError(
                    f"dense slot {slot.name!r} expects dim={slot.dim}, "
                    f"line has {n}")
            out.append(np.asarray(vals, np.float32))
    if i != len(toks):
        raise ValueError(f"{len(toks) - i} trailing tokens on line")
    return out


def _pack_batch(slots: Sequence[Slot], chunk) -> Dict[str, object]:
    """Parsed samples -> one feed batch: sparse slots as (flat values,
    lod offsets), dense slots stacked [B, dim]."""
    out: Dict[str, object] = {}
    for j, slot in enumerate(slots):
        vals = [s[j] for s in chunk]
        if slot.is_sparse:
            lod = np.zeros(len(vals) + 1, np.int64)
            np.cumsum([len(v) for v in vals], out=lod[1:])
            flat = (np.concatenate(vals) if lod[-1]
                    else np.zeros((0,), np.uint64))
            out[slot.name] = (flat, lod)
        else:
            out[slot.name] = np.stack(vals)
    return out


class InMemoryDataset:
    """Load → (shuffle) → batch, all host-side (the PS ingestion path is
    CPU-bound by design; the TPU never sees raw ids)."""

    def __init__(self, slots: Sequence[Slot]):
        if not slots:
            raise ValueError("need at least one slot")
        self._slots = list(slots)
        self._samples: List[list] = []
        self._shuffle_epoch = 0

    def __len__(self):
        return len(self._samples)

    @property
    def slots(self):
        return list(self._slots)

    def add_samples(self, lines):
        for line in lines:
            line = line.strip()
            if line:
                self._samples.append(
                    parse_multi_slot_line(line, self._slots))

    def load_from_files(self, paths: Sequence[str]):
        for p in paths:
            with open(p) as f:
                self.add_samples(f)

    def local_shuffle(self, seed=None):
        random.Random(seed).shuffle(self._samples)

    def global_shuffle(self, store, world_size: int, rank: int,
                       seed: int = 0, name: str = "ds_shuffle",
                       timeout: float = 120.0):
        """Reference InMemoryDataset::GlobalShuffle semantics: every
        sample (each rank may hold a DIFFERENT shard) is redistributed to
        a pseudo-random destination rank. Samples travel through the
        rendezvous store: rank r publishes one pickled bundle per
        destination, then collects the bundles addressed to it."""
        import pickle

        # per-call epoch keys: repeated shuffles with the same name must
        # not overwrite bundles a slower rank hasn't collected yet
        epoch = self._shuffle_epoch
        self._shuffle_epoch += 1
        pfx = f"{name}/e{epoch}"
        # reclaim epoch e-2's barrier keys: every rank entering epoch e
        # has fully completed e-1 (its 'posted' barrier), which in turn
        # required completing ALL of e-2 — so nobody can still be waiting
        # on e-2's go keys. (e-1's keys may still have waiters in-flight.)
        if epoch >= 2:
            old = f"{name}/e{epoch - 2}"
            store.delete_barrier(f"{old}/posted")
            store.delete_barrier(f"{old}/collected")
        rng = random.Random(seed + rank * 7919)   # per-rank stream is fine:
        # destinations only need to be ~uniform, not agreed on
        outgoing: List[List[list]] = [[] for _ in range(world_size)]
        for s in self._samples:
            outgoing[rng.randrange(world_size)].append(s)
        for dest in range(world_size):
            store.set(f"{pfx}/from{rank}/to{dest}",
                      pickle.dumps(outgoing[dest]))
        store.barrier(f"{pfx}/posted", world_size=world_size, rank=rank,
                      timeout=timeout)
        gathered: List[list] = []
        for src in range(world_size):
            blob = store.wait(f"{pfx}/from{src}/to{rank}",
                              timeout=timeout)
            gathered.extend(pickle.loads(blob))
        # everyone collected -> each rank reclaims the bundles it posted
        store.barrier(f"{pfx}/collected", world_size=world_size, rank=rank,
                      timeout=timeout)
        for dest in range(world_size):
            store.delete_key(f"{pfx}/from{rank}/to{dest}")
        self._samples = gathered
        self.local_shuffle(seed=seed + rank + 1)

    def batches(self, batch_size: int, drop_last: bool = False
                ) -> Iterator[Dict[str, object]]:
        """Sparse slots -> (flat values, lod offsets); dense -> [B, dim]."""
        for start in range(0, len(self._samples), batch_size):
            chunk = self._samples[start:start + batch_size]
            if drop_last and len(chunk) < batch_size:
                return
            yield _pack_batch(self._slots, chunk)


class QueueDataset:
    """Streaming MultiSlot dataset (reference QueueDataset
    framework/data_set.h / python fleet/dataset: files stream through a
    feed queue in order, nothing is materialized and shuffle is
    unsupported — the contract that distinguishes it from
    InMemoryDataset). Parses lazily file-by-file."""

    def __init__(self, slots: Sequence[Slot]):
        if not slots:
            raise ValueError("need at least one slot")
        self._slots = list(slots)
        self._filelist: List[str] = []

    @property
    def slots(self):
        return list(self._slots)

    def set_filelist(self, paths: Sequence[str]):
        self._filelist = list(paths)

    def local_shuffle(self, seed=None):
        raise RuntimeError("QueueDataset streams files in order; use "
                           "InMemoryDataset for shuffling (the reference "
                           "raises the same way)")

    global_shuffle = local_shuffle

    def _samples(self):
        for p in self._filelist:
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield parse_multi_slot_line(line, self._slots)

    def batches(self, batch_size: int, drop_last: bool = False
                ) -> Iterator[Dict[str, object]]:
        chunk: List[list] = []
        for s in self._samples():
            chunk.append(s)
            if len(chunk) == batch_size:
                yield _pack_batch(self._slots, chunk)
                chunk = []
        if chunk and not drop_last:
            yield _pack_batch(self._slots, chunk)
