"""Sharded checkpointing: save/restore pytrees of (possibly sharded) jax
arrays across mesh-shape changes.

Reference analog: fluid.io save/load_persistables + save/load ops
(/root/reference/python/paddle/fluid/io.py:239-995,
operators/save_op.cc) and the fleet HDFS checkpoint utilities
(fleet/utils/fs.py, framework/io/fs.cc). The reference pickles full
host-side tensors; that breaks once ZeRO/TP shard parameters so no process
holds a whole array. TPU-native design:

* each process writes ONLY its addressable shards (replica 0 of each) as
  `.npy` files named by the shard's global offsets;
* `meta.json` records every array's global shape/dtype/PartitionSpec and
  the shard-file index;
* restore targets an ARBITRARY mesh: `jax.make_array_from_callback` pulls
  exactly the slices each new device needs, read lazily through numpy
  memmaps — resuming ZeRO-2 on a different dp size re-tiles shards without
  materialising full arrays (beyond the largest per-device slice).

Layout: `{path}/meta.json` + `{path}/{escaped_name}__{offsets}.npy`.
Nested trees (optimizer slot dicts) flatten with '/' joined keys.
"""
from __future__ import annotations

import json
import os
import re

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["save_sharded", "load_sharded", "save_checkpoint",
           "load_checkpoint"]


def _flatten(tree, prefix=""):
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = v
    return out


def _unflatten(flat):
    out = {}
    for k, v in flat.items():
        parts = k.split("/")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out


def _escape(name):
    return re.sub(r"[^A-Za-z0-9_.-]", "_", name)


def _spec_to_json(sharding):
    if isinstance(sharding, NamedSharding):
        return [list(ax) if isinstance(ax, tuple) else ax
                for ax in sharding.spec]
    return None


def _spec_from_json(spec_json, ndim):
    if spec_json is None:
        return P(*([None] * ndim))
    axes = [tuple(ax) if isinstance(ax, list) else ax for ax in spec_json]
    axes += [None] * (ndim - len(axes))
    return P(*axes)


def save_sharded(path, tree, step=0, meta=None):
    """Write a (nested) dict of jax arrays; each process stores only its
    addressable, replica-0 shards and ITS OWN shard index
    (`index.{pid}.json`) — indices merge at load, so no process needs to
    know about shards it cannot address (multi-host safe)."""
    flat = _flatten(tree)
    os.makedirs(path, exist_ok=True)
    pid = jax.process_index()

    index = {}
    for name, arr in flat.items():
        arr = jnp.asarray(arr)
        entry = {"shape": list(arr.shape),
                 "dtype": str(arr.dtype),
                 "spec": _spec_to_json(getattr(arr, "sharding", None)),
                 "shards": []}
        if not hasattr(arr, "addressable_shards") or arr.ndim == 0:
            fname = f"{_escape(name)}__full.npy"
            if pid == 0:
                np.save(os.path.join(path, fname),
                        np.asarray(jax.device_get(arr)))
            entry["shards"].append({"file": fname,
                                    "start": [0] * arr.ndim,
                                    "stop": list(arr.shape)})
        else:
            seen = set()
            for sh in arr.addressable_shards:
                starts = tuple((idx.start or 0) for idx in sh.index)
                stops = tuple(
                    (idx.stop if idx.stop is not None else dim)
                    for idx, dim in zip(sh.index, arr.shape))
                if starts in seen or sh.replica_id != 0:
                    continue
                seen.add(starts)
                fname = (f"{_escape(name)}__"
                         + "_".join(str(s) for s in starts) + ".npy")
                np.save(os.path.join(path, fname), np.asarray(sh.data))
                entry["shards"].append({"file": fname,
                                        "start": list(starts),
                                        "stop": list(stops)})
        index[name] = entry

    with open(os.path.join(path, f"index.{pid}.json"), "w") as f:
        json.dump(index, f, indent=1)
    if pid == 0:
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump({"step": int(step), "meta": meta or {},
                       "n_processes": jax.process_count()}, f, indent=1)


def _read_slice(path, entry, starts, stops, dtype):
    """Assemble the [starts:stops) slice of one array from its shard files
    via memmaps (reads only overlapping bytes)."""
    shape = tuple(b - a for a, b in zip(starts, stops))
    out = np.zeros(shape, dtype=dtype)
    for sh in entry["shards"]:
        s0, s1 = sh["start"], sh["stop"]
        lo = [max(a, b) for a, b in zip(starts, s0)]
        hi = [min(a, b) for a, b in zip(stops, s1)]
        if any(l >= h for l, h in zip(lo, hi)):
            continue
        mm = np.load(os.path.join(path, sh["file"]), mmap_mode="r")
        src = tuple(slice(l - a, h - a) for l, h, a in zip(lo, hi, s0))
        dst = tuple(slice(l - a, h - a) for l, h, a in zip(lo, hi, starts))
        out[dst] = mm[src]
    return out


def load_sharded(path, mesh: Mesh = None, shardings=None):
    """Restore the tree. With `mesh`, arrays land sharded per their SAVED
    PartitionSpecs re-bound to the new mesh (any device count whose axis
    names match); `shardings` ({flat_name: Sharding}) overrides per array;
    with neither, arrays come back as host-local jnp arrays.

    Returns (tree, step, meta)."""
    with open(os.path.join(path, "meta.json")) as f:
        header = json.load(f)
    # merge every process's shard index (multi-host: each wrote its own)
    arrays = {}
    import glob as _glob
    for idx_file in sorted(_glob.glob(os.path.join(path, "index.*.json"))):
        with open(idx_file) as f:
            for name, entry in json.load(f).items():
                if name not in arrays:
                    arrays[name] = entry
                else:
                    known = {tuple(s["start"])
                             for s in arrays[name]["shards"]}
                    arrays[name]["shards"].extend(
                        s for s in entry["shards"]
                        if tuple(s["start"]) not in known)
    shardings = shardings or {}

    flat = {}
    for name, entry in arrays.items():
        shape = tuple(entry["shape"])
        dtype = np.dtype(entry["dtype"])
        target = shardings.get(name)
        if target is None and mesh is not None:
            spec = _spec_from_json(entry["spec"], len(shape))
            # drop axes the new mesh doesn't have
            axes = [ax if (ax is None or
                           all(a in mesh.shape for a in
                               (ax if isinstance(ax, tuple) else (ax,))))
                    else None for ax in spec]
            target = NamedSharding(mesh, P(*axes))
        if target is None:
            flat[name] = jnp.asarray(_read_slice(
                path, entry, (0,) * len(shape), shape, dtype))
        else:
            def cb(index, entry=entry, shape=shape, dtype=dtype):
                starts = tuple((ix.start or 0) for ix in index)
                stops = tuple(ix.stop if ix.stop is not None else dim
                              for ix, dim in zip(index, shape))
                return _read_slice(path, entry, starts, stops, dtype)

            flat[name] = jax.make_array_from_callback(shape, target, cb)
    return _unflatten(flat), header["step"], header["meta"]


# ---------------------------------------------------------------------------
# train-state convenience wrappers (params + optimizer slots + buffers)
# ---------------------------------------------------------------------------

def save_checkpoint(path, params, opt_state=None, state=None, step=0,
                    meta=None):
    tree = {"params": params}
    if opt_state:
        tree["opt"] = opt_state
    if state:
        tree["state"] = state
    save_sharded(path, tree, step=step, meta=meta)


def load_checkpoint(path, mesh=None, shardings=None):
    """shardings may be {"params": {...}, "opt": {...}} nested or flat."""
    flat_sh = _flatten(shardings) if shardings else None
    tree, step, meta = load_sharded(path, mesh=mesh, shardings=flat_sh)
    return (tree.get("params", {}), tree.get("opt", {}),
            tree.get("state", {}), step, meta)
