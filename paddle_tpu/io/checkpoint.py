"""Sharded checkpointing: save/restore pytrees of (possibly sharded) jax
arrays across mesh-shape changes — with an atomic, checksummed commit
protocol so a preemption mid-save can never produce a checkpoint that
`latest_checkpoint()` selects but `load_checkpoint()` cannot read.

Reference analog: fluid.io save/load_persistables + save/load ops
(/root/reference/python/paddle/fluid/io.py:239-995,
operators/save_op.cc) and the fleet HDFS checkpoint utilities
(fleet/utils/fs.py, framework/io/fs.cc). The reference pickles full
host-side tensors; that breaks once ZeRO/TP shard parameters so no process
holds a whole array. TPU-native design:

* each process writes ONLY its addressable shards (replica 0 of each) as
  `.npy` files named by the shard's global offsets;
* `meta.json` records every array's global shape/dtype/PartitionSpec and
  the shard-file index;
* restore targets an ARBITRARY mesh: `jax.make_array_from_callback` pulls
  exactly the slices each new device needs, read lazily through numpy
  memmaps — resuming ZeRO-2 on a different dp size re-tiles shards without
  materialising full arrays (beyond the largest per-device slice).

Atomic commit protocol (docs/fault_tolerance.md):

1. everything is written into `{path}.tmp`;
2. each shard entry records the file's byte size and crc32 in the
   per-process index; every file (and the directory) is fsynced;
3. `meta.json` is written LAST, then the directory renames to `{path}`
   in one atomic step.

A crash at any point leaves either the previous checkpoint untouched
plus a `.tmp` orphan (garbage-collected by retention), or the complete
new checkpoint. `latest_checkpoint()` validates manifests and returns
the newest *valid* step; `gc_checkpoints(keep_last=k)` bounds disk use.

Layout: `{path}/meta.json` + `{path}/{escaped_name}__{offsets}.npy`.
Nested trees (optimizer slot dicts) flatten with '/' joined keys.

Multi-host note: every process writes into the shared `{path}.tmp`;
process 0 performs the commit rename. Callers must barrier between the
last writer finishing and process 0 committing (the fleet compiler's
save path is single-controller per host group, which already orders
this); single-process training needs nothing.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import warnings
import zlib

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..testing import chaos

__all__ = ["save_sharded", "load_sharded", "save_checkpoint",
           "load_checkpoint", "CheckpointError", "validate_checkpoint",
           "is_valid_checkpoint", "list_checkpoints", "latest_checkpoint",
           "gc_checkpoints"]

FORMAT_VERSION = 2      # 1 = pre-checksum (still loadable/validatable)


class CheckpointError(RuntimeError):
    """A checkpoint directory is missing, incomplete, or corrupt."""


def _flatten(tree, prefix=""):
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = v
    return out


def _unflatten(flat):
    out = {}
    for k, v in flat.items():
        parts = k.split("/")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out


def _escape(name):
    return re.sub(r"[^A-Za-z0-9_.-]", "_", name)


def _spec_to_json(sharding):
    if isinstance(sharding, NamedSharding):
        return [list(ax) if isinstance(ax, tuple) else ax
                for ax in sharding.spec]
    return None


def _spec_from_json(spec_json, ndim):
    if spec_json is None:
        return P(*([None] * ndim))
    axes = [tuple(ax) if isinstance(ax, list) else ax for ax in spec_json]
    axes += [None] * (ndim - len(axes))
    return P(*axes)


# -- integrity plumbing -------------------------------------------------------

def _file_crc32(path) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _fsync_file(path):
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:        # pragma: no cover - fs without fsync support
        pass


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:        # pragma: no cover
        pass


def _save_npy(dirpath, fname, array) -> dict:
    """Write one shard file and return its manifest fields. The
    `ckpt.write` chaos site models a torn/failed shard write."""
    chaos.maybe_fail("ckpt.write", fname)
    full = os.path.join(dirpath, fname)
    np.save(full, array)
    _fsync_file(full)
    return {"size": os.path.getsize(full), "crc32": _file_crc32(full)}


def _commit_dir(work, final):
    """Atomically publish `work` as `final`. An existing `final` is
    renamed aside first so a valid directory exists at every instant."""
    chaos.maybe_fail("ckpt.rename", final)
    if os.path.exists(final):
        aside = final + ".old"
        shutil.rmtree(aside, ignore_errors=True)
        os.rename(final, aside)
        os.rename(work, final)
        shutil.rmtree(aside, ignore_errors=True)
    else:
        os.rename(work, final)
    _fsync_dir(os.path.dirname(final) or ".")


def save_sharded(path, tree, step=0, meta=None, atomic=True):
    """Write a (nested) dict of jax arrays; each process stores only its
    addressable, replica-0 shards and ITS OWN shard index
    (`index.{pid}.json`) — indices merge at load, so no process needs to
    know about shards it cannot address (multi-host safe).

    With `atomic` (default), everything goes into `{path}.tmp` and
    process 0 rename-commits after writing `meta.json` last; per-file
    sizes + crc32 checksums land in the index so load/validate can
    reject torn writes."""
    flat = _flatten(tree)
    final = path.rstrip("/")
    work = final + ".tmp" if atomic else final
    pid = jax.process_index()
    if atomic and pid == 0:
        shutil.rmtree(work, ignore_errors=True)   # stale orphan
    os.makedirs(work, exist_ok=True)

    index = {}
    for name, arr in flat.items():
        arr = jnp.asarray(arr)
        entry = {"shape": list(arr.shape),
                 "dtype": str(arr.dtype),
                 "spec": _spec_to_json(getattr(arr, "sharding", None)),
                 "shards": []}
        if not hasattr(arr, "addressable_shards") or arr.ndim == 0:
            fname = f"{_escape(name)}__full.npy"
            shard = {"file": fname, "start": [0] * arr.ndim,
                     "stop": list(arr.shape)}
            if pid == 0:
                shard.update(_save_npy(work, fname,
                                       np.asarray(jax.device_get(arr))))
            entry["shards"].append(shard)
        else:
            seen = set()
            for sh in arr.addressable_shards:
                starts = tuple((idx.start or 0) for idx in sh.index)
                stops = tuple(
                    (idx.stop if idx.stop is not None else dim)
                    for idx, dim in zip(sh.index, arr.shape))
                if starts in seen or sh.replica_id != 0:
                    continue
                seen.add(starts)
                fname = (f"{_escape(name)}__"
                         + "_".join(str(s) for s in starts) + ".npy")
                shard = {"file": fname, "start": list(starts),
                         "stop": list(stops)}
                shard.update(_save_npy(work, fname, np.asarray(sh.data)))
                entry["shards"].append(shard)
        index[name] = entry

    idx_path = os.path.join(work, f"index.{pid}.json")
    with open(idx_path, "w") as f:
        json.dump(index, f, indent=1)
    _fsync_file(idx_path)
    if pid == 0:
        meta_path = os.path.join(work, "meta.json")
        with open(meta_path, "w") as f:
            json.dump({"step": int(step), "meta": meta or {},
                       "format": FORMAT_VERSION,
                       "n_processes": jax.process_count()}, f, indent=1)
        _fsync_file(meta_path)
        _fsync_dir(work)
        if atomic:
            _commit_dir(work, final)


# -- validation / discovery / retention --------------------------------------

def validate_checkpoint(path, deep=True):
    """Raise `CheckpointError` unless `path` is a complete checkpoint:
    parseable meta.json, at least one parseable index, every indexed
    shard file present with its recorded size — and, with `deep`, its
    recorded crc32. Pre-checksum (format 1) checkpoints validate on
    existence alone."""
    if not os.path.isdir(path):
        raise CheckpointError(f"{path}: not a directory")
    try:
        with open(os.path.join(path, "meta.json")) as f:
            json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointError(f"{path}: bad meta.json ({e})") from e
    import glob as _glob
    idx_files = sorted(_glob.glob(os.path.join(path, "index.*.json")))
    if not idx_files:
        raise CheckpointError(f"{path}: no index files")
    for idx_file in idx_files:
        try:
            with open(idx_file) as f:
                index = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointError(
                f"{path}: bad {os.path.basename(idx_file)} ({e})") from e
        for name, entry in index.items():
            for sh in entry["shards"]:
                fp = os.path.join(path, sh["file"])
                if not os.path.isfile(fp):
                    raise CheckpointError(
                        f"{path}: {name} shard {sh['file']} missing")
                if "size" in sh and os.path.getsize(fp) != sh["size"]:
                    raise CheckpointError(
                        f"{path}: {sh['file']} size "
                        f"{os.path.getsize(fp)} != recorded {sh['size']}")
                if deep and "crc32" in sh and _file_crc32(fp) != sh["crc32"]:
                    raise CheckpointError(
                        f"{path}: {sh['file']} crc mismatch (torn or "
                        "corrupt write)")


def is_valid_checkpoint(path, deep=True) -> bool:
    try:
        validate_checkpoint(path, deep=deep)
        return True
    except CheckpointError:
        return False


def list_checkpoints(ckpt_dir):
    """All committed `step_{n}` directories under `ckpt_dir` (no
    validation), newest step first, as (step, path) pairs. `.tmp`/`.old`
    work directories never appear."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        if not name.startswith("step_") or "." in name:
            continue
        try:
            s = int(name.split("_", 1)[1])
        except ValueError:
            continue
        out.append((s, os.path.join(ckpt_dir, name)))
    return sorted(out, reverse=True)


def latest_checkpoint(ckpt_dir, validate=True, deep=True):
    """Newest step-numbered checkpoint under `ckpt_dir` that passes
    manifest validation (newest first, so at most the broken suffix is
    scanned); invalid candidates are skipped with a warning. Returns the
    path, or None."""
    for step, path in list_checkpoints(ckpt_dir):
        if not validate:
            if os.path.exists(os.path.join(path, "meta.json")):
                return path
            continue
        try:
            validate_checkpoint(path, deep=deep)
            return path
        except CheckpointError as e:
            warnings.warn(f"skipping invalid checkpoint: {e}")
    return None


def gc_checkpoints(ckpt_dir, keep_last, protect=()):
    """Retention: delete all but the newest `keep_last` committed
    checkpoints, plus any orphaned `.tmp`/`.old` work directories.
    Paths in `protect` survive regardless. Best-effort (a half-deleted
    old step is harmless — it is older than every kept one)."""
    if not keep_last or not os.path.isdir(ckpt_dir):
        return
    protect = {os.path.abspath(p) for p in protect}
    kept = 0
    for step, path in list_checkpoints(ckpt_dir):
        if kept < keep_last:
            kept += 1                    # protected entries count too
        elif os.path.abspath(path) not in protect:
            shutil.rmtree(path, ignore_errors=True)
    for name in os.listdir(ckpt_dir):
        if name.startswith("step_") and (name.endswith(".tmp")
                                         or name.endswith(".old")):
            shutil.rmtree(os.path.join(ckpt_dir, name), ignore_errors=True)


def _read_slice(path, entry, starts, stops, dtype):
    """Assemble the [starts:stops) slice of one array from its shard files
    via memmaps (reads only overlapping bytes)."""
    shape = tuple(b - a for a, b in zip(starts, stops))
    out = np.zeros(shape, dtype=dtype)
    for sh in entry["shards"]:
        s0, s1 = sh["start"], sh["stop"]
        lo = [max(a, b) for a, b in zip(starts, s0)]
        hi = [min(a, b) for a, b in zip(stops, s1)]
        if any(l >= h for l, h in zip(lo, hi)):
            continue
        mm = np.load(os.path.join(path, sh["file"]), mmap_mode="r")
        src = tuple(slice(l - a, h - a) for l, h, a in zip(lo, hi, s0))
        dst = tuple(slice(l - a, h - a) for l, h, a in zip(lo, hi, starts))
        out[dst] = mm[src]
    return out


def load_sharded(path, mesh: Mesh = None, shardings=None, validate=True):
    """Restore the tree. With `mesh`, arrays land sharded per their SAVED
    PartitionSpecs re-bound to the new mesh (any device count whose axis
    names match); `shardings` ({flat_name: Sharding}) overrides per array;
    with neither, arrays come back as host-local jnp arrays.

    `validate` (default) verifies the manifest (sizes + checksums) up
    front and raises `CheckpointError` on a torn or corrupt checkpoint —
    callers like `elastic.run_with_recovery` catch it and fall back to
    the previous step.

    Returns (tree, step, meta)."""
    if validate:
        validate_checkpoint(path)
    try:
        with open(os.path.join(path, "meta.json")) as f:
            header = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointError(f"{path}: bad meta.json ({e})") from e
    # merge every process's shard index (multi-host: each wrote its own)
    arrays = {}
    import glob as _glob
    for idx_file in sorted(_glob.glob(os.path.join(path, "index.*.json"))):
        with open(idx_file) as f:
            for name, entry in json.load(f).items():
                if name not in arrays:
                    arrays[name] = entry
                else:
                    known = {tuple(s["start"])
                             for s in arrays[name]["shards"]}
                    arrays[name]["shards"].extend(
                        s for s in entry["shards"]
                        if tuple(s["start"]) not in known)
    shardings = shardings or {}

    flat = {}
    for name, entry in arrays.items():
        shape = tuple(entry["shape"])
        dtype = np.dtype(entry["dtype"])
        target = shardings.get(name)
        if target is None and mesh is not None:
            spec = _spec_from_json(entry["spec"], len(shape))
            # drop axes the new mesh doesn't have
            axes = [ax if (ax is None or
                           all(a in mesh.shape for a in
                               (ax if isinstance(ax, tuple) else (ax,))))
                    else None for ax in spec]
            target = NamedSharding(mesh, P(*axes))
        if target is None:
            flat[name] = jnp.asarray(_read_slice(
                path, entry, (0,) * len(shape), shape, dtype))
        else:
            def cb(index, entry=entry, shape=shape, dtype=dtype):
                starts = tuple((ix.start or 0) for ix in index)
                stops = tuple(ix.stop if ix.stop is not None else dim
                              for ix, dim in zip(index, shape))
                return _read_slice(path, entry, starts, stops, dtype)

            flat[name] = jax.make_array_from_callback(shape, target, cb)
    return _unflatten(flat), header["step"], header["meta"]


# ---------------------------------------------------------------------------
# train-state convenience wrappers (params + optimizer slots + buffers)
# ---------------------------------------------------------------------------

def save_checkpoint(path, params, opt_state=None, state=None, step=0,
                    meta=None, keep_last=None):
    """Atomic checkpoint of the train state. With `keep_last=k` and a
    `step_{n}`-named `path`, older sibling checkpoints beyond the newest
    k (this one included) are garbage-collected after the commit."""
    tree = {"params": params}
    if opt_state:
        tree["opt"] = opt_state
    if state:
        tree["state"] = state
    save_sharded(path, tree, step=step, meta=meta)
    if keep_last and re.fullmatch(r"step_\d+",
                                  os.path.basename(path.rstrip("/"))):
        gc_checkpoints(os.path.dirname(path.rstrip("/")) or ".", keep_last,
                       protect=(path,))


def load_checkpoint(path, mesh=None, shardings=None, validate=True):
    """shardings may be {"params": {...}, "opt": {...}} nested or flat."""
    flat_sh = _flatten(shardings) if shardings else None
    tree, step, meta = load_sharded(path, mesh=mesh, shardings=flat_sh,
                                    validate=validate)
    return (tree.get("params", {}), tree.get("opt", {}),
            tree.get("state", {}), step, meta)
