"""Filesystem abstraction for checkpoint/artifact IO.

Reference: the HDFS/local FS layer distributed checkpoints route through
(/root/reference/python/paddle/distributed/fleet/utils/fs.py — FS base,
LocalFS, HDFSClient with ls_dir/is_file/mkdirs/delete/mv/upload/download;
C++ twin framework/io/fs.cc). On TPU deployments the remote store is
GCS/NFS-fuse rather than HDFS; the abstraction stays so checkpoint code
is store-agnostic:

    fs = LocalFS()                       # or any FS subclass
    fs.mkdirs(dir); fs.put(path, bytes); fs.get(path)
    save_checkpoint(..., fs=...)         # io/checkpoint.py accepts one

A GCSFS/HDFS client would subclass FS with the same verbs; none ships in
this zero-egress build (mount the bucket via FUSE and use LocalFS — the
standard TPU-VM pattern).
"""
from __future__ import annotations

import os
import shutil
from typing import List

__all__ = ["FS", "LocalFS", "sync_dir"]


class FS:
    """Store-agnostic verbs (reference FS base: fs.py:33)."""

    def ls_dir(self, path) -> List[str]:
        raise NotImplementedError

    def is_file(self, path) -> bool:
        raise NotImplementedError

    def is_dir(self, path) -> bool:
        raise NotImplementedError

    def is_exist(self, path) -> bool:
        raise NotImplementedError

    def mkdirs(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def mv(self, src, dst, overwrite=False):
        raise NotImplementedError

    def put(self, path, data: bytes):
        """Write bytes atomically (publish-on-rename)."""
        raise NotImplementedError

    def get(self, path) -> bytes:
        raise NotImplementedError

    # reference API keeps distinct upload/download for remote stores;
    # for byte-level stores they alias put/get of local files
    def upload(self, local_path, remote_path):
        with open(local_path, "rb") as f:
            self.put(remote_path, f.read())

    def download(self, remote_path, local_path):
        d = os.path.dirname(local_path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(local_path, "wb") as f:
            f.write(self.get(remote_path))

    def touch(self, path):
        self.put(path, b"")


class LocalFS(FS):
    """Local/NFS/FUSE-mounted filesystem (reference LocalFS fs.py:100)."""

    def ls_dir(self, path):
        if not os.path.isdir(path):
            return []
        return sorted(os.listdir(path))

    def is_file(self, path):
        return os.path.isfile(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.unlink(path)

    def mv(self, src, dst, overwrite=False):
        if os.path.exists(dst):
            if not overwrite:
                raise FileExistsError(f"mv: {dst} exists")
            self.delete(dst)
        d = os.path.dirname(dst)
        if d:
            os.makedirs(d, exist_ok=True)
        shutil.move(src, dst)

    def put(self, path, data):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)          # atomic publish

    def get(self, path):
        with open(path, "rb") as f:
            return f.read()


def sync_dir(src_dir: str, dst_dir: str, fs: FS = None):
    """Mirror a finished checkpoint directory into `dst_dir` through an FS
    (reference: fleet checkpoint upload via HDFSClient). Files are
    published atomically one by one; call after save_checkpoint returns."""
    fs = fs or LocalFS()
    local = LocalFS()
    fs.mkdirs(dst_dir)
    for name in local.ls_dir(src_dir):
        p = os.path.join(src_dir, name)
        if local.is_file(p):
            fs.put(os.path.join(dst_dir, name), local.get(p))
