"""Filesystem abstraction for checkpoint/artifact IO.

Reference: the HDFS/local FS layer distributed checkpoints route through
(/root/reference/python/paddle/distributed/fleet/utils/fs.py — FS base,
LocalFS, HDFSClient with ls_dir/is_file/mkdirs/delete/mv/upload/download;
C++ twin framework/io/fs.cc). On TPU deployments the remote store is
GCS/NFS-fuse rather than HDFS; the abstraction stays so checkpoint code
is store-agnostic:

    fs = LocalFS()                       # or any FS subclass
    fs.mkdirs(dir); fs.put(path, bytes); fs.get(path)
    sync_dir(ckpt_dir, mounted_bucket)   # mirror a finished checkpoint

A GCSFS/HDFS client would subclass FS with the same verbs; none ships in
this zero-egress build (mount the bucket via FUSE and use LocalFS — the
standard TPU-VM pattern).
"""
from __future__ import annotations

import os
import shutil
from typing import List

from ..testing import chaos

__all__ = ["FS", "LocalFS", "RemoteFS", "HDFSClient", "sync_dir"]


class FS:
    """Store-agnostic verbs (reference FS base: fs.py:33)."""

    def ls_dir(self, path) -> List[str]:
        raise NotImplementedError

    def is_file(self, path) -> bool:
        raise NotImplementedError

    def is_dir(self, path) -> bool:
        raise NotImplementedError

    def is_exist(self, path) -> bool:
        raise NotImplementedError

    def mkdirs(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def mv(self, src, dst, overwrite=False):
        raise NotImplementedError

    def put(self, path, data: bytes):
        """Write bytes atomically (publish-on-rename)."""
        raise NotImplementedError

    def get(self, path) -> bytes:
        raise NotImplementedError

    # reference API keeps distinct upload/download for remote stores;
    # for byte-level stores they alias put/get of local files
    def upload(self, local_path, remote_path):
        with open(local_path, "rb") as f:
            self.put(remote_path, f.read())

    def download(self, remote_path, local_path):
        d = os.path.dirname(local_path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(local_path, "wb") as f:
            f.write(self.get(remote_path))

    def touch(self, path, exist_ok=True):
        """Create an empty file; an existing file is left untouched when
        exist_ok (reference LocalFS.touch semantics, fs.py:319)."""
        if self.is_exist(path):
            if exist_ok:
                return
            raise FileExistsError(f"touch: {path} exists")
        self.put(path, b"")

    def put_file(self, local_src, path):
        """Publish a local file to `path` (subclasses may stream)."""
        with open(local_src, "rb") as f:
            self.put(path, f.read())


class LocalFS(FS):
    """Local/NFS/FUSE-mounted filesystem (reference LocalFS fs.py:100)."""

    def ls_dir(self, path):
        if not os.path.isdir(path):
            return []
        return sorted(os.listdir(path))

    def is_file(self, path):
        return os.path.isfile(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.unlink(path)

    def mv(self, src, dst, overwrite=False):
        if os.path.exists(dst) and not overwrite:
            raise FileExistsError(f"mv: {dst} exists")
        d = os.path.dirname(dst)
        if d:
            os.makedirs(d, exist_ok=True)
        if os.path.isfile(src) and not os.path.isdir(dst):
            os.replace(src, dst)       # atomic, dst never absent
            return
        if os.path.exists(dst):
            # directories: keep a valid dst at every instant — rename the
            # old one aside, move the new in, then reclaim
            aside = dst + ".old"
            shutil.rmtree(aside, ignore_errors=True)
            os.replace(dst, aside) if os.path.isfile(dst) else \
                os.rename(dst, aside)
            shutil.move(src, dst)
            self.delete(aside)
        else:
            shutil.move(src, dst)

    def put(self, path, data):
        chaos.maybe_fail("fs.put", path)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        import tempfile
        fd, tmp = tempfile.mkstemp(dir=d or ".",
                                   prefix=os.path.basename(path) + ".")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)      # atomic publish, unique tmp name
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def put_file(self, local_src, path):
        chaos.maybe_fail("fs.put", path)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        import tempfile
        fd, tmp = tempfile.mkstemp(dir=d or ".",
                                   prefix=os.path.basename(path) + ".")
        os.close(fd)
        try:
            shutil.copyfile(local_src, tmp)    # streams in chunks
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def get(self, path):
        with open(path, "rb") as f:
            return f.read()


class RemoteFS(FS):
    """Remote object/file store over an fsspec filesystem — the GCS/S3/
    HDFS analog of the reference HDFSClient
    (/root/reference/python/paddle/distributed/fleet/utils/fs.py:419,
    which shells out to `hadoop fs`). Pass an fsspec protocol ("gs",
    "s3", "hdfs", "memory", "file", ...) plus its storage options; every
    FS verb maps onto the fsspec call, so sharded checkpoint save/load
    (`sync_dir`, io.checkpoint) runs against any mounted or remote store.

    Every idempotent verb retries transient store faults (OSError /
    ConnectionError / TimeoutError) with bounded exponential backoff +
    jitter via utils.retry — a flaky RPC degrades to a short stall, not
    a failed checkpoint mirror. `retries=0` opts out. `mv` stays
    single-shot (not idempotent: a retry after a half-applied rename
    would fail spuriously or clobber).

    fsspec is import-guarded: constructing a RemoteFS without the
    package (or without the protocol's driver) raises a clear error;
    importing this module never does."""

    #: transient-fault allowlist for retries (NOT FileExistsError etc. —
    #: those are real answers, retrying them can't help)
    _TRANSIENT = (ConnectionError, TimeoutError, OSError)

    def __init__(self, protocol: str = "file", retries: int = 3,
                 retry_base_delay: float = 0.1, **storage_options):
        try:
            import fsspec
        except ImportError as e:          # pragma: no cover
            raise ImportError(
                "RemoteFS needs the 'fsspec' package for remote-store "
                "access; install it or use LocalFS over a FUSE mount"
            ) from e
        self._fs = fsspec.filesystem(protocol, **storage_options)
        self.protocol = protocol
        self._retries = retries
        self._retry_base_delay = retry_base_delay

    def _retry(self, fn, *args, **kwargs):
        from ..utils.retry import retry_call
        return retry_call(fn, *args, retries=self._retries,
                          base_delay=self._retry_base_delay,
                          retry_on=self._TRANSIENT, **kwargs)

    def ls_dir(self, path):
        if not self.is_dir(path):
            return []
        return sorted(os.path.basename(p.rstrip("/"))
                      for p in self._retry(self._fs.ls, path, detail=False))

    def is_file(self, path):
        return self._retry(self._fs.isfile, path)

    def is_dir(self, path):
        return self._retry(self._fs.isdir, path)

    def is_exist(self, path):
        return self._retry(self._fs.exists, path)

    def mkdirs(self, path):
        self._retry(self._fs.makedirs, path, exist_ok=True)

    def delete(self, path):
        def _del():
            if self._fs.exists(path):
                self._fs.rm(path, recursive=True)
        self._retry(_del)

    def mv(self, src, dst, overwrite=False):
        if self._fs.exists(dst):
            if not overwrite:
                raise FileExistsError(f"mv: {dst} exists")
            self._fs.rm(dst, recursive=True)
        self._fs.mv(src, dst, recursive=True)

    def put(self, path, data: bytes):
        def _put():
            chaos.maybe_fail("fs.put", path)
            parent = os.path.dirname(path.rstrip("/"))
            if parent:
                self._fs.makedirs(parent, exist_ok=True)
            with self._fs.open(path, "wb") as f:
                f.write(data)
        self._retry(_put)

    def get(self, path) -> bytes:
        def _get():
            with self._fs.open(path, "rb") as f:
                return f.read()
        return self._retry(_get)

    def put_file(self, local_src, path):
        def _put():
            chaos.maybe_fail("fs.put", path)
            parent = os.path.dirname(path.rstrip("/"))
            if parent:
                self._fs.makedirs(parent, exist_ok=True)
            self._fs.put_file(local_src, path)
        self._retry(_put)

    def download(self, remote_path, local_path):
        d = os.path.dirname(local_path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._retry(self._fs.get_file, remote_path, local_path)

    # reference-API surface (fs.py:95-110)
    def rename(self, src, dst):
        self.mv(src, dst, overwrite=False)

    def need_upload_download(self):
        return True

    def list_dirs(self, path):
        return [n for n in self.ls_dir(path)
                if self.is_dir(os.path.join(path, n))]

    def upload_dir(self, local_dir, dest_dir):
        sync_dir(local_dir, dest_dir, fs=self)


class HDFSClient(RemoteFS):
    """Name-parity client for reference code (fleet/utils/fs.py:419
    `HDFSClient(hadoop_home, configs)`): the same constructor shape,
    backed by fsspec's hdfs driver — or any protocol via `protocol=`
    (on TPU deployments the store is usually gs://)."""

    def __init__(self, hadoop_home=None, configs=None, time_out=5 * 60,
                 sleep_inter=1000, protocol: str = "hdfs",
                 **storage_options):
        configs = configs or {}
        if protocol == "hdfs" and configs.get("fs.default.name"):
            # hdfs://host:port out of the hadoop config dict
            from urllib.parse import urlparse
            u = urlparse(configs["fs.default.name"])
            storage_options.setdefault("host", u.hostname or "default")
            if u.port:
                storage_options.setdefault("port", u.port)
        super().__init__(protocol, **storage_options)


def sync_dir(src_dir: str, dst_dir: str, fs: FS = None):
    """Mirror a finished checkpoint directory into `dst_dir` through an FS
    (reference: fleet checkpoint upload via HDFSClient), recursively.

    Publish order makes the mirror pollable: data files first, index.*
    next, meta.json LAST — a reader that waits for meta.json never sees
    an index pointing at missing shards. Each file streams through the
    FS put_file path (no whole-file bytes in memory for LocalFS)."""
    fs = fs or LocalFS()
    local = LocalFS()
    fs.mkdirs(dst_dir)

    files, subdirs = [], []
    for name in local.ls_dir(src_dir):
        p = os.path.join(src_dir, name)
        (subdirs if local.is_dir(p) else files).append(name)
    for name in subdirs:
        sync_dir(os.path.join(src_dir, name),
                 os.path.join(dst_dir, name), fs=fs)

    def rank(name):
        if name == "meta.json":
            return 2
        if name.startswith("index."):
            return 1
        return 0

    for name in sorted(files, key=rank):
        fs.put_file(os.path.join(src_dir, name),
                    os.path.join(dst_dir, name))
