"""paddle.io parity surface."""
from .dataloader import DataLoader, default_collate_fn, get_worker_info, \
    device_prefetch  # noqa: F401
from .dataset import (ChainDataset, ComposeDataset, ConcatDataset, Dataset,
                      IterableDataset, Subset, TensorDataset,
                      random_split)  # noqa: F401
from .sampler import (BatchSampler, DistributedBatchSampler, RandomSampler,
                      Sampler, SequenceSampler, SubsetRandomSampler,
                      WeightedRandomSampler)  # noqa: F401
from . import crypto  # noqa: F401  (model encryption, io/crypto/)
from .data_feed import Slot, InMemoryDataset  # noqa: F401  (PS data path)
from .fs import FS, LocalFS, sync_dir  # noqa: F401  (fs abstraction)

