"""paddle.incubate.reader (reference fluid/contrib/reader):
decorator-style reader pipeline helpers over python generators."""
from __future__ import annotations

__all__ = ["cache", "buffered", "compose", "chain", "shuffle",
           "xmap_readers", "ComposeNotAligned"]


def cache(reader):
    """Materialize a reader's items once, replay from memory. The cache
    publishes only on a COMPLETED pass — abandoned or interleaved first
    passes cannot corrupt it."""
    state = {"items": None}

    def new_reader():
        if state["items"] is not None:
            yield from state["items"]
            return
        local = []
        for it in reader():
            local.append(it)
            yield it
        state["items"] = local
    return new_reader


def buffered(reader, size):
    """Prefetch up to `size` items on a background thread."""
    import queue
    import threading

    def new_reader():
        q = queue.Queue(maxsize=size)
        END = object()
        err = []

        def fill():
            try:
                for it in reader():
                    q.put(it)
            except BaseException as e:      # propagate to the consumer
                err.append(e)
            finally:
                q.put(END)
        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            it = q.get()
            if it is END:
                break
            yield it
        if err:
            raise err[0]
    return new_reader


class ComposeNotAligned(ValueError):
    """Raised when composed readers end at different lengths
    (reference fluid/reader compose check_alignment)."""


def compose(*readers, check_alignment=True):
    """Zip readers: yields tuples of one item from each; by default a
    length mismatch raises ComposeNotAligned like the reference."""
    def new_reader():
        gens = [r() for r in readers]
        while True:
            outs, stops = [], 0
            for g in gens:
                try:
                    outs.append(next(g))
                except StopIteration:
                    stops += 1
                    outs.append(None)
            if stops == len(gens):
                return
            if stops:
                if check_alignment:
                    raise ComposeNotAligned(
                        "composed readers have different lengths")
                return
            flat = []
            for it in outs:
                if isinstance(it, tuple):
                    flat.extend(it)
                else:
                    flat.append(it)
            yield tuple(flat)
    return new_reader


def chain(*readers):
    def new_reader():
        for r in readers:
            yield from r()
    return new_reader


def shuffle(reader, buf_size):
    import random

    def new_reader():
        buf = []
        for it in reader():
            buf.append(it)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        random.shuffle(buf)
        yield from buf
    return new_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader (thread pool; the reference uses
    threads too)."""
    from concurrent.futures import ThreadPoolExecutor

    def new_reader():
        with ThreadPoolExecutor(max_workers=process_num) as pool:
            yield from pool.map(mapper, reader())
    return new_reader
