"""Model-file encryption (reference: framework/io/crypto/cipher.cc +
pybind/crypto.cc — CryptoPP AES behind CipherFactory; python surface
paddle.fluid.core.CipherFactory). Dependency-free build: ChaCha20
(RFC 7539) in native/chacha20.cpp, compiled on first use.

    from paddle_tpu.io import crypto
    key = crypto.CipherFactory.generate_key()        # 32 bytes
    cipher = crypto.CipherFactory.create_cipher()
    cipher.encrypt_to_file(plain_bytes, key, "model.enc")
    plain = cipher.decrypt_from_file(key, "model.enc")

`paddle.save/load(..., cipher_key=...)` route through this module.
File layout: magic "PDTC" | u8 version | 12B nonce | 16B tag | ciphertext.
Version 2: the tag is the RFC 8439 ChaCha20-Poly1305 AEAD tag (empty
AAD, one-time key from the counter-0 block) — authenticated encryption,
not just corruption detection. Version-1 files (pre-Poly1305 tag) are
rejected; re-encrypt with the current build.
"""
from __future__ import annotations

import ctypes
import os
import secrets
import subprocess

__all__ = ["Cipher", "CipherFactory", "encrypt", "decrypt",
           "encrypt_to_file", "decrypt_from_file"]

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "native")
_MAGIC = b"PDTC"
_VERSION = 2
_lib = None


def _load_lib():
    global _lib
    if _lib is not None:
        return _lib
    src = os.path.join(_NATIVE_DIR, "chacha20.cpp")
    so = os.path.join(_NATIVE_DIR, "chacha20.so")
    if (not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(src)):
        res = subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", so, src],
            capture_output=True, text=True)
        if res.returncode != 0:
            raise RuntimeError(f"chacha20 build failed:\n{res.stderr}")
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        # stale/foreign-platform artifact (e.g. copied checkout): rebuild
        os.unlink(so)
        return _load_lib()
    lib.pd_chacha20_xor.restype = ctypes.c_int
    lib.pd_chacha20_mac.restype = ctypes.c_int
    lib.pd_poly1305.restype = ctypes.c_int
    _lib = lib
    return lib


def _keystream_xor(key: bytes, nonce: bytes, data: bytes,
                   counter: int = 1) -> bytes:
    lib = _load_lib()
    buf = ctypes.create_string_buffer(bytes(data), len(data))
    lib.pd_chacha20_xor(key, nonce, ctypes.c_uint32(counter), buf,
                        ctypes.c_uint64(len(data)))
    return buf.raw


def _mac(key: bytes, nonce: bytes, data: bytes) -> bytes:
    lib = _load_lib()
    tag = ctypes.create_string_buffer(16)
    lib.pd_chacha20_mac(key, nonce, bytes(data),
                        ctypes.c_uint64(len(data)), tag)
    return tag.raw


def _check_key(key: bytes) -> bytes:
    key = bytes(key)
    if len(key) != 32:
        raise ValueError(f"cipher key must be 32 bytes, got {len(key)}")
    return key


def encrypt(data: bytes, key: bytes) -> bytes:
    """magic|version|nonce|tag|ciphertext (encrypt-then-MAC)."""
    key = _check_key(key)
    nonce = secrets.token_bytes(12)
    ct = _keystream_xor(key, nonce, bytes(data))
    tag = _mac(key, nonce, ct)
    return _MAGIC + bytes([_VERSION]) + nonce + tag + ct


def decrypt(blob: bytes, key: bytes) -> bytes:
    key = _check_key(key)
    if blob[:4] != _MAGIC or len(blob) < 4 + 1 + 12 + 16:
        raise ValueError("not a paddle_tpu encrypted blob")
    if blob[4] != _VERSION:
        raise ValueError(f"unsupported cipher version {blob[4]}")
    nonce = blob[5:17]
    tag = blob[17:33]
    ct = blob[33:]
    import hmac as _hmac
    if not _hmac.compare_digest(_mac(key, nonce, ct), tag):
        raise ValueError("decryption failed: wrong key or corrupted file")
    return _keystream_xor(key, nonce, ct)


def encrypt_to_file(data: bytes, key: bytes, path: str):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        f.write(encrypt(data, key))


def decrypt_from_file(key: bytes, path: str) -> bytes:
    with open(path, "rb") as f:
        return decrypt(f.read(), key)


class Cipher:
    """Reference Cipher surface (cipher.h: Encrypt/Decrypt +
    EncryptToFile/DecryptFromFile)."""

    def encrypt(self, plaintext: bytes, key: bytes) -> bytes:
        return encrypt(plaintext, key)

    def decrypt(self, ciphertext: bytes, key: bytes) -> bytes:
        return decrypt(ciphertext, key)

    def encrypt_to_file(self, plaintext: bytes, key: bytes, path: str):
        encrypt_to_file(plaintext, key, path)

    def decrypt_from_file(self, key: bytes, path: str) -> bytes:
        return decrypt_from_file(key, path)


class CipherFactory:
    """Reference CipherFactory::CreateCipher parity."""

    @staticmethod
    def create_cipher(config_file: str = None) -> Cipher:
        return Cipher()

    @staticmethod
    def generate_key() -> bytes:
        return secrets.token_bytes(32)
