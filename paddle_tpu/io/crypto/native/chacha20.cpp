// ChaCha20-Poly1305 (RFC 7539/8439) for model-file encryption.
//
// Reference capability: AES cipher for saved programs/params
// (/root/reference/paddle/fluid/framework/io/crypto/cipher.cc,
//  cipher_utils.cc, pybind/crypto.cc — CryptoPP AES-CBC/GCM).
// This build is dependency-free, so the cipher is ChaCha20: a public
// RFC-specified design that is small enough to implement exactly and is
// not table-driven (no cache-timing side channels). Integrity is the
// RFC 8439 AEAD construction with empty AAD: Poly1305 keyed by the
// counter-0 keystream block (data encryption starts at counter 1) over
// ciphertext || pad16 || le64(aad_len=0) || le64(ct_len).
//
// C ABI (ctypes): all functions return 0 on success.
//   pd_chacha20_xor(key32, nonce12, counter, buf, n)   in-place XOR
//   pd_chacha20_mac(key32, nonce12, buf, n, tag16)     AEAD-style tag
//   pd_poly1305(key32, msg, n, tag16)                  raw Poly1305

#include <stdint.h>
#include <string.h>

namespace {

inline uint32_t rotl(uint32_t x, int n) {
  return (x << n) | (x >> (32 - n));
}

inline uint32_t load32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

inline void store32(uint8_t* p, uint32_t v) {
  p[0] = static_cast<uint8_t>(v);
  p[1] = static_cast<uint8_t>(v >> 8);
  p[2] = static_cast<uint8_t>(v >> 16);
  p[3] = static_cast<uint8_t>(v >> 24);
}

#define QR(a, b, c, d)        \
  a += b; d ^= a; d = rotl(d, 16); \
  c += d; b ^= c; b = rotl(b, 12); \
  a += b; d ^= a; d = rotl(d, 8);  \
  c += d; b ^= c; b = rotl(b, 7)

void chacha20_block(const uint8_t key[32], const uint8_t nonce[12],
                    uint32_t counter, uint8_t out[64]) {
  // RFC 7539 §2.3: constants | key | counter | nonce
  uint32_t st[16] = {0x61707865u, 0x3320646eu, 0x79622d32u, 0x6b206574u};
  for (int i = 0; i < 8; ++i) st[4 + i] = load32(key + 4 * i);
  st[12] = counter;
  for (int i = 0; i < 3; ++i) st[13 + i] = load32(nonce + 4 * i);

  uint32_t x[16];
  memcpy(x, st, sizeof(x));
  for (int round = 0; round < 10; ++round) {  // 20 rounds = 10 double
    QR(x[0], x[4], x[8], x[12]);
    QR(x[1], x[5], x[9], x[13]);
    QR(x[2], x[6], x[10], x[14]);
    QR(x[3], x[7], x[11], x[15]);
    QR(x[0], x[5], x[10], x[15]);
    QR(x[1], x[6], x[11], x[12]);
    QR(x[2], x[7], x[8], x[13]);
    QR(x[3], x[4], x[9], x[14]);
  }
  for (int i = 0; i < 16; ++i) store32(out + 4 * i, x[i] + st[i]);
}

// Poly1305 (RFC 7539 §2.5), 26-bit-limb schoolbook form: h = (h + m) * r
// mod 2^130 - 5 per 16-byte block, then h + s mod 2^128.
struct Poly1305 {
  uint32_t r[5], s4[4];   // clamped r; s4[i] = r[i+1] * 5
  uint32_t h[5] = {0, 0, 0, 0, 0};
  uint8_t pad[16];        // key high half, added at the end
  uint8_t buf[16];
  uint64_t buflen = 0;

  explicit Poly1305(const uint8_t key[32]) {
    uint32_t t0 = load32(key), t1 = load32(key + 4), t2 = load32(key + 8),
             t3 = load32(key + 12);
    r[0] = t0 & 0x3ffffff;
    r[1] = ((t0 >> 26) | (t1 << 6)) & 0x3ffff03;
    r[2] = ((t1 >> 20) | (t2 << 12)) & 0x3ffc0ff;
    r[3] = ((t2 >> 14) | (t3 << 18)) & 0x3f03fff;
    r[4] = (t3 >> 8) & 0x00fffff;
    for (int i = 0; i < 4; ++i) s4[i] = r[i + 1] * 5;
    memcpy(pad, key + 16, 16);
  }

  void block(const uint8_t m[16], uint32_t hibit) {
    uint32_t t0 = load32(m), t1 = load32(m + 4), t2 = load32(m + 8),
             t3 = load32(m + 12);
    h[0] += t0 & 0x3ffffff;
    h[1] += ((t0 >> 26) | (t1 << 6)) & 0x3ffffff;
    h[2] += ((t1 >> 20) | (t2 << 12)) & 0x3ffffff;
    h[3] += ((t2 >> 14) | (t3 << 18)) & 0x3ffffff;
    h[4] += (t3 >> 8) | hibit;
    uint64_t d[5];
    d[0] = (uint64_t)h[0] * r[0] + (uint64_t)h[1] * s4[3] +
           (uint64_t)h[2] * s4[2] + (uint64_t)h[3] * s4[1] +
           (uint64_t)h[4] * s4[0];
    d[1] = (uint64_t)h[0] * r[1] + (uint64_t)h[1] * r[0] +
           (uint64_t)h[2] * s4[3] + (uint64_t)h[3] * s4[2] +
           (uint64_t)h[4] * s4[1];
    d[2] = (uint64_t)h[0] * r[2] + (uint64_t)h[1] * r[1] +
           (uint64_t)h[2] * r[0] + (uint64_t)h[3] * s4[3] +
           (uint64_t)h[4] * s4[2];
    d[3] = (uint64_t)h[0] * r[3] + (uint64_t)h[1] * r[2] +
           (uint64_t)h[2] * r[1] + (uint64_t)h[3] * r[0] +
           (uint64_t)h[4] * s4[3];
    d[4] = (uint64_t)h[0] * r[4] + (uint64_t)h[1] * r[3] +
           (uint64_t)h[2] * r[2] + (uint64_t)h[3] * r[1] +
           (uint64_t)h[4] * r[0];
    uint64_t c = 0;
    for (int i = 0; i < 5; ++i) {
      d[i] += c;
      h[i] = d[i] & 0x3ffffff;
      c = d[i] >> 26;
    }
    h[0] += static_cast<uint32_t>(c * 5);
    c = h[0] >> 26;
    h[0] &= 0x3ffffff;
    h[1] += static_cast<uint32_t>(c);
  }

  void update(const uint8_t* m, uint64_t n) {
    if (buflen) {
      uint64_t take = 16 - buflen < n ? 16 - buflen : n;
      memcpy(buf + buflen, m, take);
      buflen += take;
      m += take;
      n -= take;
      if (buflen == 16) {
        block(buf, 1u << 24);
        buflen = 0;
      }
    }
    while (n >= 16) {
      block(m, 1u << 24);
      m += 16;
      n -= 16;
    }
    if (n) {
      memcpy(buf, m, n);
      buflen = n;
    }
  }

  void final(uint8_t tag[16]) {
    if (buflen) {   // short last block: append 1, zero-pad, hibit = 0
      uint8_t last[16] = {0};
      memcpy(last, buf, buflen);
      last[buflen] = 1;
      block(last, 0);
    }
    uint32_t c;
    c = h[1] >> 26; h[1] &= 0x3ffffff; h[2] += c;
    c = h[2] >> 26; h[2] &= 0x3ffffff; h[3] += c;
    c = h[3] >> 26; h[3] &= 0x3ffffff; h[4] += c;
    c = h[4] >> 26; h[4] &= 0x3ffffff; h[0] += c * 5;
    c = h[0] >> 26; h[0] &= 0x3ffffff; h[1] += c;
    // g = h + 5 - 2^130; pick g when h >= p (no borrow out of g4)
    uint32_t g[5];
    g[0] = h[0] + 5; c = g[0] >> 26; g[0] &= 0x3ffffff;
    g[1] = h[1] + c; c = g[1] >> 26; g[1] &= 0x3ffffff;
    g[2] = h[2] + c; c = g[2] >> 26; g[2] &= 0x3ffffff;
    g[3] = h[3] + c; c = g[3] >> 26; g[3] &= 0x3ffffff;
    g[4] = h[4] + c - (1u << 26);
    uint32_t mask = (g[4] >> 31) - 1;   // all-ones iff no borrow
    for (int i = 0; i < 5; ++i) h[i] = (h[i] & ~mask) | (g[i] & mask);
    // serialize to 128 bits, add the pad with carry
    uint32_t t0 = h[0] | (h[1] << 26);
    uint32_t t1 = (h[1] >> 6) | (h[2] << 20);
    uint32_t t2 = (h[2] >> 12) | (h[3] << 14);
    uint32_t t3 = (h[3] >> 18) | (h[4] << 8);
    uint64_t f;
    f = (uint64_t)t0 + load32(pad);            store32(tag, (uint32_t)f);
    f = (uint64_t)t1 + load32(pad + 4) + (f >> 32);
    store32(tag + 4, (uint32_t)f);
    f = (uint64_t)t2 + load32(pad + 8) + (f >> 32);
    store32(tag + 8, (uint32_t)f);
    f = (uint64_t)t3 + load32(pad + 12) + (f >> 32);
    store32(tag + 12, (uint32_t)f);
  }
};

}  // namespace

extern "C" {

int pd_chacha20_xor(const uint8_t* key, const uint8_t* nonce,
                    uint32_t counter, uint8_t* buf, uint64_t n) {
  uint8_t block[64];
  uint64_t off = 0;
  while (off < n) {
    chacha20_block(key, nonce, counter++, block);
    uint64_t take = n - off < 64 ? n - off : 64;
    for (uint64_t i = 0; i < take; ++i) buf[off + i] ^= block[i];
    off += take;
  }
  return 0;
}

// Raw Poly1305 (exported for RFC 7539 §2.5.2 vector tests).
int pd_poly1305(const uint8_t* key, const uint8_t* msg, uint64_t n,
                uint8_t tag[16]) {
  Poly1305 p(key);
  p.update(msg, n);
  p.final(tag);
  return 0;
}

// RFC 8439 §2.8 AEAD tag (empty AAD): Poly1305 keyed by the counter-0
// keystream block over ct || pad16(ct) || le64(0) || le64(len(ct)).
// Encryption starts at counter 1, so the one-time key block is never
// reused as keystream.
int pd_chacha20_mac(const uint8_t* key, const uint8_t* nonce,
                    const uint8_t* buf, uint64_t n, uint8_t tag[16]) {
  uint8_t otk[64];
  chacha20_block(key, nonce, 0, otk);
  Poly1305 p(otk);
  p.update(buf, n);
  static const uint8_t zeros[16] = {0};
  if (n % 16) p.update(zeros, 16 - (n % 16));
  uint8_t lens[16];
  memset(lens, 0, 8);                       // aad length = 0
  for (int i = 0; i < 8; ++i)
    lens[8 + i] = static_cast<uint8_t>(n >> (8 * i));
  p.update(lens, 16);
  p.final(tag);
  return 0;
}

}  // extern "C"
