// ChaCha20 stream cipher (RFC 7539) + Poly1305-free keyed integrity tag
// (HMAC-style over the keystream) for model-file encryption.
//
// Reference capability: AES cipher for saved programs/params
// (/root/reference/paddle/fluid/framework/io/crypto/cipher.cc,
//  cipher_utils.cc, pybind/crypto.cc — CryptoPP AES-CBC/GCM).
// This build is dependency-free, so the cipher is ChaCha20: a public
// RFC-specified design that is small enough to implement exactly and is
// not table-driven (no cache-timing side channels). Integrity uses a
// simple encrypt-then-MAC with a second ChaCha20 block as the key.
//
// C ABI (ctypes): all functions return 0 on success.
//   pd_chacha20_xor(key32, nonce12, counter, buf, n)   in-place XOR
//   pd_chacha20_mac(key32, nonce12, buf, n, tag16)     keystream MAC

#include <stdint.h>
#include <string.h>

namespace {

inline uint32_t rotl(uint32_t x, int n) {
  return (x << n) | (x >> (32 - n));
}

inline uint32_t load32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

inline void store32(uint8_t* p, uint32_t v) {
  p[0] = static_cast<uint8_t>(v);
  p[1] = static_cast<uint8_t>(v >> 8);
  p[2] = static_cast<uint8_t>(v >> 16);
  p[3] = static_cast<uint8_t>(v >> 24);
}

#define QR(a, b, c, d)        \
  a += b; d ^= a; d = rotl(d, 16); \
  c += d; b ^= c; b = rotl(b, 12); \
  a += b; d ^= a; d = rotl(d, 8);  \
  c += d; b ^= c; b = rotl(b, 7)

void chacha20_block(const uint8_t key[32], const uint8_t nonce[12],
                    uint32_t counter, uint8_t out[64]) {
  // RFC 7539 §2.3: constants | key | counter | nonce
  uint32_t st[16] = {0x61707865u, 0x3320646eu, 0x79622d32u, 0x6b206574u};
  for (int i = 0; i < 8; ++i) st[4 + i] = load32(key + 4 * i);
  st[12] = counter;
  for (int i = 0; i < 3; ++i) st[13 + i] = load32(nonce + 4 * i);

  uint32_t x[16];
  memcpy(x, st, sizeof(x));
  for (int round = 0; round < 10; ++round) {  // 20 rounds = 10 double
    QR(x[0], x[4], x[8], x[12]);
    QR(x[1], x[5], x[9], x[13]);
    QR(x[2], x[6], x[10], x[14]);
    QR(x[3], x[7], x[11], x[15]);
    QR(x[0], x[5], x[10], x[15]);
    QR(x[1], x[6], x[11], x[12]);
    QR(x[2], x[7], x[8], x[13]);
    QR(x[3], x[4], x[9], x[14]);
  }
  for (int i = 0; i < 16; ++i) store32(out + 4 * i, x[i] + st[i]);
}

}  // namespace

extern "C" {

int pd_chacha20_xor(const uint8_t* key, const uint8_t* nonce,
                    uint32_t counter, uint8_t* buf, uint64_t n) {
  uint8_t block[64];
  uint64_t off = 0;
  while (off < n) {
    chacha20_block(key, nonce, counter++, block);
    uint64_t take = n - off < 64 ? n - off : 64;
    for (uint64_t i = 0; i < take; ++i) buf[off + i] ^= block[i];
    off += take;
  }
  return 0;
}

// Keyed tag: mix the ciphertext into a keystream-derived state (this is a
// lightweight integrity check against corruption/wrong key, not an AEAD
// proof — the reference's CBC mode had none at all).
int pd_chacha20_mac(const uint8_t* key, const uint8_t* nonce,
                    const uint8_t* buf, uint64_t n, uint8_t tag[16]) {
  uint8_t block[64];
  chacha20_block(key, nonce, 0xffffffffu, block);  // counter outside data use
  uint32_t h[4] = {load32(block), load32(block + 4), load32(block + 8),
                   load32(block + 12)};
  for (uint64_t i = 0; i < n; ++i) {
    uint32_t b = buf[i] + 1;
    h[i & 3] = rotl(h[i & 3] ^ (b * 0x9e3779b1u), 13) * 0x85ebca6bu;
  }
  // fold in the length and finalize
  h[0] ^= static_cast<uint32_t>(n);
  h[1] ^= static_cast<uint32_t>(n >> 32);
  for (int r = 0; r < 4; ++r)
    for (int i = 0; i < 4; ++i)
      h[i] = rotl(h[i] ^ h[(i + 1) & 3], 11) * 0xc2b2ae35u;
  for (int i = 0; i < 4; ++i) store32(tag + 4 * i, h[i]);
  return 0;
}

}  // extern "C"
