"""paddle.optimizer parity surface."""
from . import lr  # noqa: F401
from .optimizer import Optimizer  # noqa: F401
from .optimizers import (SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb,
                         Lars, Momentum, RMSProp)  # noqa: F401
