"""Concrete optimizers (reference: python/paddle/optimizer/{sgd,momentum,adam,
adamw,lamb,rmsprop,adagrad,adadelta,adamax}.py; kernels operators/optimizers/).
Each is a pair of pure functions over one param — see optimizer.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Lamb", "RMSProp", "Adagrad",
           "Adadelta", "Adamax", "Lars"]


class SGD(Optimizer):
    def apply_one(self, p, g, s, lr, wd):
        if wd:
            g = g + wd * p
        return p - lr * g, s


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_state(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def apply_one(self, p, g, s, lr, wd):
        if wd:
            g = g + wd * p
        v = self._momentum * s["velocity"] + g
        if self._nesterov:
            update = g + self._momentum * v
        else:
            update = v
        return p - lr * update, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_state(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p),
                "beta1_pow": jnp.ones([], jnp.float32),
                "beta2_pow": jnp.ones([], jnp.float32)}

    def _adam_core(self, p, g, s, lr):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        m = b1 * s["moment1"] + (1 - b1) * g
        v = b2 * s["moment2"] + (1 - b2) * (g * g)
        b1p = s["beta1_pow"] * b1
        b2p = s["beta2_pow"] * b2
        mhat = m / (1 - b1p).astype(p.dtype)
        vhat = v / (1 - b2p).astype(p.dtype)
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + eps)
        return new_p, {"moment1": m, "moment2": v, "beta1_pow": b1p,
                       "beta2_pow": b2p}

    def apply_one(self, p, g, s, lr, wd):
        if wd:  # coupled L2 (reference Adam regularization path)
            g = g + wd * p
        return self._adam_core(p, g, s, lr)


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        self._wd = weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun
        self._coupled_wd = None
        self._decoupled_wd = weight_decay

    def apply_one(self, p, g, s, lr, wd):
        new_p, new_s = self._adam_core(p, g, s, lr)
        new_p = new_p - lr * self._decoupled_wd * p
        return new_p, new_s


class Lamb(Optimizer):
    """reference: operators/optimizers/lamb_op.h (trust-ratio Adam)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_state(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p),
                "beta1_pow": jnp.ones([], jnp.float32),
                "beta2_pow": jnp.ones([], jnp.float32)}

    def apply_one(self, p, g, s, lr, wd):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        m = b1 * s["moment1"] + (1 - b1) * g
        v = b2 * s["moment2"] + (1 - b2) * (g * g)
        b1p = s["beta1_pow"] * b1
        b2p = s["beta2_pow"] * b2
        mhat = m / (1 - b1p).astype(p.dtype)
        vhat = v / (1 - b2p).astype(p.dtype)
        r = mhat / (jnp.sqrt(vhat) + eps) + self._wd * p
        p_norm = jnp.linalg.norm(p)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        return p - lr * trust * r, {"moment1": m, "moment2": v,
                                    "beta1_pow": b1p, "beta2_pow": b2p}


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def init_state(self, p):
        s = {"mean_square": jnp.zeros_like(p),
             "momentum": jnp.zeros_like(p)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(p)
        return s

    def apply_one(self, p, g, s, lr, wd):
        if wd:
            g = g + wd * p
        ms = self._rho * s["mean_square"] + (1 - self._rho) * g * g
        if self._centered:
            mg = self._rho * s["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._eps)
        else:
            mg = None
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * s["momentum"] + lr * g / denom
        new_s = {"mean_square": ms, "momentum": mom}
        if self._centered:
            new_s["mean_grad"] = mg
        return p - mom, new_s


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def init_state(self, p):
        return {"moment": jnp.full_like(p, self._init_acc)}

    def apply_one(self, p, g, s, lr, wd):
        if wd:
            g = g + wd * p
        acc = s["moment"] + g * g
        return p - lr * g / (jnp.sqrt(acc) + self._eps), {"moment": acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._eps, self._rho = epsilon, rho

    def init_state(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p),
                "avg_squared_update": jnp.zeros_like(p)}

    def apply_one(self, p, g, s, lr, wd):
        if wd:
            g = g + wd * p
        asg = self._rho * s["avg_squared_grad"] + (1 - self._rho) * g * g
        update = g * jnp.sqrt(s["avg_squared_update"] + self._eps) / \
            jnp.sqrt(asg + self._eps)
        asu = self._rho * s["avg_squared_update"] + (1 - self._rho) * \
            update * update
        return p - lr * update, {"avg_squared_grad": asg,
                                 "avg_squared_update": asu}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_state(self, p):
        return {"moment": jnp.zeros_like(p), "inf_norm": jnp.zeros_like(p),
                "beta1_pow": jnp.ones([], jnp.float32)}

    def apply_one(self, p, g, s, lr, wd):
        if wd:
            g = g + wd * p
        m = self._beta1 * s["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * s["inf_norm"], jnp.abs(g) + self._eps)
        b1p = s["beta1_pow"] * self._beta1
        new_p = p - lr / (1 - b1p).astype(p.dtype) * m / u
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class Lars(Momentum):
    """LARS (reference: operators/optimizers/lars_momentum_op.*;
    fleet lars meta-optimizer)."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005, parameters=None,
                 grad_clip=None, exclude_from_weight_decay=None,
                 epsilon=1e-9, multi_precision=False, name=None):
        super().__init__(learning_rate, momentum, parameters, False, None,
                         grad_clip, multi_precision, name)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._lars_eps = epsilon

    def apply_one(self, p, g, s, lr, wd):
        p_norm = jnp.linalg.norm(p)
        g_norm = jnp.linalg.norm(g)
        local_lr = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            self._lars_coeff * p_norm /
            (g_norm + self._lars_wd * p_norm + self._lars_eps), 1.0)
        g_eff = g + self._lars_wd * p
        v = self._momentum * s["velocity"] + lr * local_lr * g_eff
        return p - v, {"velocity": v}
