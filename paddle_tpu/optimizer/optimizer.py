"""Optimizer base (reference: python/paddle/optimizer/optimizer.py:48; CUDA
kernels operators/optimizers/*).

TPU-native design: every optimizer is defined by two pure functions
  init_state(param)                  -> per-param state pytree
  apply_one(param, grad, state, lr)  -> (new_param, new_state)
The eager `step()` applies them per parameter (dygraph parity). The jitted
fit path calls `functional_update` on whole pytrees inside the compiled
train step — XLA fuses the update into one kernel sweep, which subsumes the
reference's fuse_optimizer_ops_pass (SURVEY.md row 22).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.errors import InvalidArgumentError, enforce
from ..core.tensor import Tensor, no_grad
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._global_reg = None
        if isinstance(weight_decay, float) or weight_decay is None:
            self._coupled_wd = weight_decay  # L2-style added to grad
        else:
            # paddle.regularizer.L1Decay/L2Decay (or any _coeff object):
            # applied grad-side in _apply_reg, NOT via the wd slot —
            # optimizers with decoupled decay (AdamW/Lamb) ignore the wd
            # argument, which would silently drop the regularizer
            self._global_reg = weight_decay
            self._coupled_wd = None
        self._state: Dict[int, dict] = {}       # id(param) -> state pytree
        self._master: Dict[int, jax.Array] = {}  # fp32 master weights
        self._accumulators_created = False

    # -- hyperparameters ----------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        enforce(not isinstance(self._learning_rate, LRScheduler),
                "cannot set_lr when using an LRScheduler",
                InvalidArgumentError)
        self._learning_rate = float(value)

    @property
    def _lr_scheduler(self):
        return self._learning_rate if isinstance(self._learning_rate,
                                                 LRScheduler) else None

    # -- subclass interface (pure) -----------------------------------------
    def init_state(self, param: jax.Array) -> dict:
        return {}

    def apply_one(self, param, grad, state, lr, wd):
        raise NotImplementedError

    # -- eager step ---------------------------------------------------------
    @no_grad()
    def step(self):
        params = self._parameter_list
        enforce(params is not None,
                "Optimizer created without a parameter list; pass "
                "parameters=model.parameters()", InvalidArgumentError)
        params_grads = [(p, p.grad) for p in params
                        if (p.grad is not None and p.trainable
                            and not p.stop_gradient)]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        for p, g in params_grads:
            if g is None:
                continue
            pid = id(p)
            if pid not in self._state:
                self._state[pid] = self.init_state(p._data)
                if self._multi_precision and p.dtype != jnp.float32:
                    self._master[pid] = p._data.astype(jnp.float32)
            arr = self._master.get(pid, p._data)
            g_arr = g._data
            if g_arr.dtype != arr.dtype:
                g_arr = g_arr.astype(arr.dtype)
            g_arr, wd = self._regularized(p, arr, g_arr)
            new_p, new_s = self.apply_one(arr, g_arr, self._state[pid], lr, wd)
            self._state[pid] = new_s
            if pid in self._master:
                self._master[pid] = new_p
                p._data = new_p.astype(p._data.dtype)
            else:
                p._data = new_p

    minimize_step = step

    def _apply_reg(self, reg, arr, g_arr):
        """(grad', wd) for one param under regularizer `reg` (may be
        None -> optimizer-wide weight_decay). A per-param
        ParamAttr(regularizer=...) overrides the optimizer-wide one
        (reference fluid/regularizer.py append_regularization_ops
        priority). Regularizer OBJECTS always apply grad-side (L1:
        coeff*sign(param), L2: coeff*param) — NOT via the wd slot, which
        decoupled-decay optimizers (AdamW/Lamb) ignore; only a plain
        float weight_decay rides the wd slot."""
        if reg is None:
            reg = self._global_reg
        if reg is not None and hasattr(reg, "_coeff"):
            # grad-side application works for EVERY optimizer (the wd
            # slot is `g + wd*p` where consumed, and ignored by the
            # decoupled-decay optimizers)
            if getattr(reg, "_l1", False):
                return g_arr + reg._coeff * jnp.sign(arr), 0.0
            return g_arr + reg._coeff * arr, 0.0
        return g_arr, (self._coupled_wd or 0.0)

    def _regularized(self, p, arr, g_arr):
        return self._apply_reg(getattr(p, "regularizer", None), arr, g_arr)

    def collect_param_regularizers(self, layer):
        """Record {param-name: regularizer} so the functional path (keyed
        by named_parameters names) honours per-param ParamAttr
        regularizers the same way the eager step() does. Called by the
        compiled-step builders (hapi / fleet)."""
        self._param_regs = {
            name: p.regularizer for name, p in layer.named_parameters()
            if getattr(p, "regularizer", None) is not None}

    @no_grad()
    def clear_grad(self, set_to_zero=False):
        if self._parameter_list:
            for p in self._parameter_list:
                p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    # -- functional pytree path (used by jitted train steps) ---------------
    def functional_init(self, params: Dict[str, jax.Array]):
        return {k: self.init_state(v) for k, v in params.items()}

    def functional_update(self, params: Dict[str, jax.Array],
                          grads: Dict[str, jax.Array], opt_state, lr=None):
        """Pure: (params, grads, state) -> (new_params, new_state).
        Safe to call inside jax.jit; `lr` may be a traced scalar."""
        if lr is None:
            lr = self.get_lr()
        if self._grad_clip is not None:
            grads = _clip_pytree(grads, self._grad_clip)
        new_params, new_state = {}, {}
        for k, p in params.items():
            g = grads.get(k)
            if g is None:
                new_params[k] = p
                new_state[k] = opt_state[k]
                continue
            if g.dtype != p.dtype:
                g = g.astype(p.dtype)
            # per-param regs resolve by name when the step builder called
            # collect_param_regularizers; otherwise the optimizer-wide
            # weight_decay applies
            g, wd = self._apply_reg(
                getattr(self, "_param_regs", {}).get(k), p, g)
            new_params[k], new_state[k] = self.apply_one(
                p, g, opt_state[k], lr, wd)
        return new_params, new_state

    # -- checkpoint ---------------------------------------------------------
    def state_dict(self):
        out = {"LR_Scheduler": (self._lr_scheduler.state_dict()
                                if self._lr_scheduler else
                                {"lr": self.get_lr()})}
        if self._parameter_list:
            name_of = {id(p): p.name for p in self._parameter_list}
            for pid, st in self._state.items():
                base = name_of.get(pid, str(pid))
                for k, v in st.items():
                    out[f"{base}_{k}"] = Tensor(v) if isinstance(
                        v, jax.Array) else v
        return out

    def set_state_dict(self, state_dict):
        sch = state_dict.get("LR_Scheduler")
        if sch and self._lr_scheduler:
            self._lr_scheduler.set_state_dict(sch)
        if not self._parameter_list:
            return
        for p in self._parameter_list:
            pid = id(p)
            st = self._state.get(pid) or self.init_state(p._data)
            loaded = {}
            for k in st:
                key = f"{p.name}_{k}"
                if key in state_dict:
                    v = state_dict[key]
                    loaded[k] = v._data if isinstance(v, Tensor) else jnp.asarray(v)
                else:
                    loaded[k] = st[k]
            self._state[pid] = loaded

    def _create_accumulators(self, *a, **k):  # legacy hook
        pass


def _clip_pytree(grads: Dict[str, jax.Array], clip):
    """Apply a ClipGradBy* object to a dict of raw grads (functional path)."""
    fake = [(None, Tensor(g)) for g in grads.values()]
    clipped = clip(fake)
    return {k: t._data for k, (_, t) in zip(grads.keys(), clipped)}
