"""Model summary + FLOPs estimate (reference: hapi/model_summary.py,
hapi/dynamic_flops.py)."""
from __future__ import annotations

import math

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


def _param_count(layer: Layer):
    return sum(int(math.prod(p.shape)) for p in layer.parameters())


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Print a per-layer table; returns {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []

    def mk_hook(name):
        def hook(layer, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
            shape = list(out.shape) if hasattr(out, "shape") else "?"
            own = sum(int(math.prod(p.shape))
                      for p in layer.parameters(include_sublayers=False))
            rows.append((name, type(layer).__name__, shape, own))
        return hook

    for name, sub in net.named_sublayers():
        hooks.append(sub.register_forward_post_hook(mk_hook(name)))

    try:
        if input is not None:
            x = input if isinstance(input, (list, tuple)) else [input]
        elif input_size is not None:
            sizes = (input_size if isinstance(input_size, list)
                     else [input_size])
            dts = dtypes if isinstance(dtypes, (list, tuple)) else \
                [dtypes] * len(sizes)
            x = [Tensor(np.zeros([d if d is not None else 1 for d in s],
                                 dtype=np.dtype(dt or "float32")))
                 for s, dt in zip(sizes, dts)]
        else:
            raise ValueError("summary needs input_size or input")
        was_training = net.training
        net.eval()
        net(*x)
        if was_training:
            net.train()
    finally:
        for h in hooks:
            h.remove()

    total = _param_count(net)
    trainable = sum(int(math.prod(p.shape)) for p in net.parameters()
                    if getattr(p, "trainable", True))
    width = max([len(r[0]) for r in rows] + [10])
    print(f"{'Layer':<{width}}  {'Type':<20} {'Output Shape':<20} Params")
    print("-" * (width + 50))
    for name, tname, shape, own in rows:
        print(f"{name:<{width}}  {tname:<20} {str(shape):<20} {own}")
    print("-" * (width + 50))
    print(f"Total params: {total:,}\nTrainable params: {trainable:,}")
    return {"total_params": total, "trainable_params": trainable}


def flops(net: Layer, input_size, custom_ops=None, print_detail=False):
    """Rough multiply-accumulate count for conv/linear layers."""
    total = [0]
    hooks = []

    def conv_hook(layer, inputs, outputs):
        out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
        k = math.prod(layer._kernel_size) if hasattr(layer, "_kernel_size") \
            else 1
        cin = getattr(layer, "_in_channels", 1)
        groups = getattr(layer, "_groups", 1)
        total[0] += int(math.prod(out.shape)) * cin // groups * k

    def linear_hook(layer, inputs, outputs):
        out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
        total[0] += int(math.prod(out.shape)) * layer.weight.shape[0]

    from ..nn.layer.conv import _ConvNd
    from ..nn.layer.common import Linear
    for _, sub in net.named_sublayers():
        if isinstance(sub, _ConvNd):
            hooks.append(sub.register_forward_post_hook(conv_hook))
        elif isinstance(sub, Linear):
            hooks.append(sub.register_forward_post_hook(linear_hook))
    try:
        x = Tensor(np.zeros([d if d is not None else 1 for d in input_size],
                            np.float32))
        was_training = net.training
        net.eval()
        net(x)
        if was_training:
            net.train()
    finally:
        for h in hooks:
            h.remove()
    if print_detail:
        print(f"FLOPs (MACs): {total[0]:,}")
    return total[0]
