"""High-level training API (reference: python/paddle/hapi/ — Model
hapi/model.py:810, fit :1299, callbacks hapi/callbacks.py)."""
from .callbacks import (Callback, EarlyStopping, LRScheduler, MetricsLogger,
                        ModelCheckpoint, ProgBarLogger)
from .model import Model
from .summary import flops, summary

__all__ = ["Model", "summary", "flops", "Callback", "ProgBarLogger",
           "ModelCheckpoint", "LRScheduler", "EarlyStopping",
           "MetricsLogger"]
