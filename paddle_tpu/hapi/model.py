"""hapi Model — fit/evaluate/predict on a jit-compiled functional step.

Reference: python/paddle/hapi/model.py:810 (Model), :1299 (fit); the
reference dispatches each batch through the dygraph tracer or a static
Program (adapters model.py:224,:609). TPU-native redesign: ONE jitted
train step — functional_call(layer) + jax.value_and_grad + the optimizer's
pure functional_update — so the whole step (fwd, bwd, update) is a single
XLA executable; buffers (BN stats) and the dropout PRNG key are threaded
functionally through the step instead of mutated.
"""
from __future__ import annotations

import os
import pickle
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..core.tensor import Tensor
from ..framework import functional_call
from ..io import DataLoader
from ..jit import compile_cache
from ..metric import Metric
from . import callbacks as cbks_mod


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _to_jax(batch):
    out = []
    for b in _as_list(batch):
        out.append(b._data if isinstance(b, Tensor) else jnp.asarray(b))
    return out


class _AsyncScalar:
    """A loss that stays on device until someone looks at it.

    fit() keeps the dispatch pipeline full by NOT fetching the loss every
    batch (each fetch is a host sync — through a remote-attached TPU it
    costs a full RTT); callbacks/logs materialise it lazily at log_freq.
    Reference analog: the monitor fetches fetch_list values only at
    Profiler/log steps, not per batch."""

    __slots__ = ("_arr", "_val")

    def __init__(self, arr):
        self._arr = arr
        self._val = None

    def __float__(self):
        if self._val is None:
            self._val = float(jax.device_get(self._arr))
            self._arr = None
        return self._val

    def __format__(self, spec):
        return format(float(self), spec)

    def __repr__(self):
        return repr(float(self))

    def __int__(self):
        return int(float(self))

    def __round__(self, ndigits=None):
        return round(float(self), ndigits)

    def __bool__(self):
        return bool(float(self))

    def __neg__(self):
        return -float(self)

    def __abs__(self):
        return abs(float(self))

    def __hash__(self):
        return hash(float(self))

    @staticmethod
    def _coerce(o):
        try:
            return float(o)
        except (TypeError, ValueError):
            return None

    def _cmp(self, o, op):
        v = self._coerce(o)
        if v is None:
            return NotImplemented
        return op(float(self), v)

    def __lt__(self, o):
        return self._cmp(o, lambda a, b: a < b)

    def __le__(self, o):
        return self._cmp(o, lambda a, b: a <= b)

    def __gt__(self, o):
        return self._cmp(o, lambda a, b: a > b)

    def __ge__(self, o):
        return self._cmp(o, lambda a, b: a >= b)

    def __eq__(self, o):
        v = self._coerce(o)
        # mirror float: incomparable operands are unequal, never an error
        return False if v is None else float(self) == v

    def __ne__(self, o):
        return not self.__eq__(o)

    def __add__(self, o):
        return self._cmp(o, lambda a, b: a + b)

    __radd__ = __add__

    def __sub__(self, o):
        return self._cmp(o, lambda a, b: a - b)

    def __rsub__(self, o):
        return self._cmp(o, lambda a, b: b - a)

    def __mul__(self, o):
        return self._cmp(o, lambda a, b: a * b)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._cmp(o, lambda a, b: a / b)

    def __rtruediv__(self, o):
        return self._cmp(o, lambda a, b: b / a)


import numbers as _numbers

_numbers.Real.register(_AsyncScalar)


class Model:
    """Wraps a Layer with train/eval/predict loops (hapi/model.py:810)."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._amp_level = "O0"
        self._jit_step = None
        self._jit_eval = None
        self._jit_pred = None
        self._grad_accum_n = 1
        self.stop_training = False

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, strategy=None):
        """strategy: a DistributedStrategy routes training through the
        fleet strategy compiler (dp/ZeRO/tp/sp/ep per its toggles).
        Metric-less evaluation runs under the SAME shardings (no host
        gather); metric evaluation and predict sync params and run
        single-device."""
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _as_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metrics must be Metric, got {type(m)}")
        if isinstance(amp_configs, str):
            self._amp_level = amp_configs
        elif isinstance(amp_configs, dict):
            self._amp_level = amp_configs.get("level", "O1")
        self._strategy = strategy
        if strategy is not None and self._metrics:
            import warnings
            if getattr(strategy, "pipeline", False):
                warnings.warn(
                    "metrics under a PIPELINE strategy evaluate on the "
                    "synced host path (the pp eval program computes only "
                    "the loss); non-pp strategies compute metrics under "
                    "the training shardings via evaluate()")
            else:
                warnings.warn(
                    "metrics are computed by evaluate() (under the "
                    "training shardings), not during fit() — the strategy "
                    "train step returns only the loss, so per-batch train "
                    "logs omit metric values")
        if strategy is not None and self._amp_level != "O0" \
                and not strategy.amp:
            import warnings
            warnings.warn(
                "amp_configs is ignored on the strategy training path; "
                "set strategy.amp=True (+ amp_configs.use_pure_bf16 for "
                "O2) instead")
        # wire the persistent XLA compile cache (PADDLE_TPU_COMPILE_CACHE,
        # default ~/.cache/paddle_tpu/xla) before the first compile
        compile_cache.setup_compilation_cache()
        self._invalidate()

    def _invalidate(self):
        self._dist_prog = None
        self._jit_step = self._jit_eval = self._jit_pred = None
        self._jit_grad = self._jit_apply = None
        self._aot_step = None
        self._retrace_guard = None
        self._compile_stats = None
        self._accum_grads = None
        self._accum_count = 0

    # -- functional plumbing -------------------------------------------
    def _split_tree(self, copy=False):
        from ..framework import param_arrays, state_arrays, unaliased_put
        params = param_arrays(self.network)
        state = state_arrays(self.network)
        if copy:
            # the jitted train step donates params: a no-copy split would
            # leave the network's own Tensors holding deleted buffers
            params = {k: unaliased_put(v) for k, v in params.items()}
        return params, state

    def _write_back(self, params, state):
        lookup = dict(self.network.named_parameters())
        lookup.update(dict(self.network.named_buffers()))
        for k, v in {**params, **state}.items():
            if k in lookup:
                lookup[k]._data = v

    def _compute_loss(self, outputs, labels):
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        if self._loss is None:
            return outs[0]
        wrapped_outs = [Tensor(o) if not isinstance(o, Tensor) else o
                        for o in outs]
        wrapped_lbls = [Tensor(l) if not isinstance(l, Tensor) else l
                        for l in labels]
        loss = self._loss(*wrapped_outs, *wrapped_lbls)
        return loss._data if isinstance(loss, Tensor) else loss

    def _build_train_step(self):
        optimizer = self._optimizer
        optimizer.collect_param_regularizers(self.network)
        amp_on = self._amp_level in ("O1", "O2")

        def train_step(params, state, opt_state, key, lr, inputs, labels):
            def loss_of(p):
                from .. import amp as amp_mod
                with random_mod.key_scope(key):
                    ctx = amp_mod.auto_cast(enable=amp_on,
                                            level=self._amp_level,
                                            dtype="bfloat16")
                    with ctx:
                        outs, new_state = functional_call(
                            self.network, p, state, *inputs)
                loss = self._compute_loss(outs, labels)
                return loss, (outs, new_state)

            (loss, (outs, new_state)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            new_params, new_opt = optimizer.functional_update(
                params, grads, opt_state, lr=lr)
            return loss, outs, new_params, new_state, new_opt

        return jax.jit(train_step,
                       donate_argnums=self._donate_argnums((0, 2), 2))

    def _build_grad_step(self):
        amp_on = self._amp_level in ("O1", "O2")

        def grad_step(params, state, key, inputs, labels):
            def loss_of(p):
                from .. import amp as amp_mod
                with random_mod.key_scope(key):
                    with amp_mod.auto_cast(enable=amp_on,
                                           level=self._amp_level,
                                           dtype="bfloat16"):
                        outs, new_state = functional_call(
                            self.network, p, state, *inputs)
                loss = self._compute_loss(outs, labels)
                return loss, (outs, new_state)

            (loss, (outs, new_state)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            return loss, outs, new_state, grads

        return jax.jit(grad_step)

    def _build_apply_step(self):
        optimizer = self._optimizer
        n_acc = self._grad_accum_n

        def apply_step(params, opt_state, grads, lr):
            grads = jax.tree_util.tree_map(lambda g: g / n_acc, grads)
            return optimizer.functional_update(params, grads, opt_state,
                                               lr=lr)

        # donate params + opt slots only: donated grads have no matching
        # output to alias for slot-less optimizers (SGD), which made XLA
        # warn "Some donated buffers were not usable" on every fit
        return jax.jit(apply_step,
                       donate_argnums=self._donate_argnums((0, 1), 1))

    def _donate_argnums(self, argnums, opt_argnum):
        """Drop the opt_state argnum when the optimizer keeps no slots
        (e.g. plain SGD): donating a leaf-less pytree arg makes XLA warn
        "Some donated buffers were not usable" on every compile."""
        opt_state = getattr(self, "_opt_state", None)
        if not jax.tree_util.tree_leaves(opt_state):
            return tuple(a for a in argnums if a != opt_argnum)
        return tuple(argnums)

    def _build_eval_step(self):
        def eval_step(params, state, inputs, labels):
            outs, _ = functional_call(self.network, params, state, *inputs)
            loss = (self._compute_loss(outs, labels)
                    if (self._loss is not None and labels) else None)
            return loss, outs

        return jax.jit(eval_step)

    # ------------------------------------------------------------------
    def _dist_train_batch(self, inputs, labels, sync=True):
        """Strategy-compiled step (reference: fleet.distributed_optimizer
        -> meta-optimizer rewrites; here compile_train_step)."""
        from ..distributed.fleet.compiler import compile_train_step

        if self._dist_prog is None:
            net, model = self.network, self

            class _LossAdapter:
                """Presents network+loss as the layer-with-a-loss-method
                protocol compile_train_step drives. param_shardings is
                delegated via __getattr__ only when the network has one —
                the compiler provides the replicated fallback."""

                def named_parameters(self, *a, **k):
                    return net.named_parameters(*a, **k)

                def named_buffers(self, *a, **k):
                    return net.named_buffers(*a, **k)

                def named_sublayers(self, *a, **k):
                    # the compiler walks these for scan-stacked params
                    return net.named_sublayers(*a, **k)

                # train/eval must reach the real network: the pipelined
                # eval builder flips the layer to eval mode around its
                # trace (dropout blocks refuse keyless TRAIN traces)
                def eval(self):
                    net.eval()

                def train(self):
                    net.train()

                @property
                def training(self):
                    return getattr(net, "training", False)

                _FORWARDED = ("param_shardings",
                              "pipeline_split_params", "pipeline_fns",
                              # manual-tp pipeline protocol (pp x tp)
                              "split_block_params_tp", "block_tp_specs",
                              "pipeline_block_fn_tp",
                              "merge_block_params_tp",
                              "pipeline_block_fn_sp",
                              # expert-parallel pipeline protocol
                              "pipeline_block_fn_ep", "block_ep_specs",
                              "pipeline_block_emits_aux", "cfg",
                              # scan-over-layers unroll escape hatch
                              "set_scan_unroll")

                def __getattr__(self, name):
                    # expose the network's sharding/pipeline protocols to
                    # the compiler only when the network implements them
                    if name in self._FORWARDED and \
                            getattr(net, name, None) is not None:
                        return getattr(net, name)
                    raise AttributeError(name)

                def loss(self, *batch):
                    k = model._dist_n_inputs
                    outs = net(*batch[:k])
                    return Tensor(model._compute_loss(outs,
                                                      list(batch[k:])))

                def loss_and_outs(self, *batch):
                    """Sharded-eval protocol: loss + forward outputs so
                    metric states accumulate without gathering params."""
                    k = model._dist_n_inputs
                    outs = net(*batch[:k])
                    first = outs[0] if isinstance(outs, (list, tuple)) \
                        else outs
                    return (Tensor(model._compute_loss(outs,
                                                       list(batch[k:]))),
                            first)

            self._dist_n_inputs = len(inputs)
            from ..distributed import mesh as mesh_mod
            mesh = mesh_mod.get_mesh()
            if mesh is not None:
                # a stale global mesh from another strategy must not
                # silently override this strategy's degrees; a mesh whose
                # device count can't even satisfy the strategy (ValueError
                # from resolve_degrees) is just as stale as one with the
                # wrong axis sizes
                try:
                    want = self._strategy.resolve_degrees(
                        len(mesh.devices.ravel()))
                except ValueError:
                    want = None
                have = {k: int(v) for k, v in mesh.shape.items()}
                if want is None or {k: v for k, v in want.items()
                                    if k in have} != have:
                    mesh = None     # compiler rebuilds from the strategy
            self._dist_prog = compile_train_step(
                _LossAdapter(), self._optimizer, self._strategy,
                mesh=mesh)
            restored = getattr(self, "_restored_opt_state", None)
            if restored is not None and \
                    set(restored) == set(self._dist_prog.opt_state) and \
                    all(set(restored[n]) ==
                        set(self._dist_prog.opt_state[n])
                        for n in restored):
                sh = self._dist_prog.shardings["opt"]
                self._dist_prog.opt_state = {
                    n: {sl: jax.device_put(jnp.asarray(v), sh[n][sl])
                        for sl, v in st.items()}
                    for n, st in restored.items()}
                self._restored_opt_state = None
        loss = self._dist_prog.step(*inputs, *labels,
                                    lr=self._optimizer.get_lr())
        self._dist_dirty = True
        return [float(jax.device_get(loss))] if sync \
            else [_AsyncScalar(loss)]

    def train_batch(self, inputs, labels=None, sync=True):
        """One optimizer step on a batch; returns [loss] (+metric updates).
        sync=False keeps the loss on device (fit's log_freq-deferred
        fetch; the returned value is float-convertible on demand)."""
        if self._optimizer is None:
            raise RuntimeError("call prepare(optimizer, loss) first")
        self.network.train()
        if getattr(self, "_strategy", None) is not None:
            if getattr(self, "_grad_accum_n", 1) > 1:
                raise ValueError(
                    "accumulate_grad_batches is not supported with a "
                    "DistributedStrategy; set strategy.gradient_merge "
                    "and gradient_merge_configs.k_steps instead")
            return self._dist_train_batch(_as_list(inputs),
                                          _as_list(labels), sync=sync)
        if self._jit_step is None:
            self._params, self._state = self._split_tree(copy=True)
            restored = getattr(self, "_restored_opt_state", None)
            if restored is not None and set(restored) == set(self._params):
                self._opt_state = jax.tree_util.tree_map(jnp.asarray, restored)
            else:
                self._opt_state = self._optimizer.functional_init(self._params)
            self._restored_opt_state = None
            # opt_state must exist first: _build_train_step derives
            # donate_argnums from whether the optimizer keeps slots
            self._jit_step = self._build_train_step()
            self._aot_step = None
            self._retrace_guard = compile_cache.RetraceGuard(
                "hapi.train_step")
        inputs = _to_jax(inputs)
        labels = _to_jax(labels)
        key = random_mod.next_key()
        lr = jnp.asarray(self._optimizer.get_lr(), jnp.float32)
        n_acc = getattr(self, "_grad_accum_n", 1)
        if n_acc > 1:
            # gradient merge (reference GradientMergeOptimizer
            # optimizer.py:5671): accumulate microbatch grads, apply every
            # n_acc batches with the mean
            if getattr(self, "_jit_grad", None) is None:
                self._jit_grad = self._build_grad_step()
                self._jit_apply = self._build_apply_step()
                self._accum_grads = None
                self._accum_count = 0
            loss, outs, self._state, grads = self._jit_grad(
                self._params, self._state, key, inputs, labels)
            self._accum_grads = grads if self._accum_grads is None else \
                jax.tree_util.tree_map(jnp.add, self._accum_grads, grads)
            self._accum_count += 1
            if self._accum_count >= n_acc:
                self._params, self._opt_state = self._jit_apply(
                    self._params, self._opt_state, self._accum_grads, lr)
                self._accum_grads = None
                self._accum_count = 0
        else:
            args = (self._params, self._state, self._opt_state,
                    key, lr, inputs, labels)
            verdict = self._retrace_guard.check(inputs=inputs,
                                                labels=labels)
            if self._aot_step is None or verdict == "retrace":
                # explicit AOT compile (timed, persistent-cache aware)
                # instead of the first-step implicit trace; the compiled
                # executable is called directly below — lowering does not
                # seed the jit wrapper's own cache
                try:
                    self._aot_step, self._compile_stats = \
                        compile_cache.aot_compile(self._jit_step, *args,
                                                  label="hapi.train_step")
                except compile_cache.RetraceError:
                    raise
                except Exception:  # exotic input: keep the implicit path
                    self._aot_step = self._jit_step
            loss, outs, self._params, self._state, self._opt_state = \
                self._aot_step(*args)
        self._update_metrics(outs, labels)
        return [float(jax.device_get(loss))] if sync \
            else [_AsyncScalar(loss)]

    def _sync_dist_if_dirty(self):
        """One host gather per train->eval transition, not per batch."""
        if getattr(self, "_dist_prog", None) is not None and \
                getattr(self, "_dist_dirty", False):
            self._dist_prog.write_back()
            self._dist_dirty = False

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        prog = getattr(self, "_dist_prog", None)
        batch0 = _as_list(inputs)[0] if _as_list(inputs) else None
        div = getattr(prog, "_eval_batch_divisor", 0) if prog else 0
        # read shape without materializing (np.asarray on a device array
        # would force a device->host copy per eval step)
        b0 = getattr(batch0, "shape", None)
        b0 = (b0[0] if b0 else
              (len(batch0) if hasattr(batch0, "__len__") else None))
        metrics_ok = (not self._metrics or
                      getattr(prog, "_eval_returns_outs", False))
        if getattr(self, "_strategy", None) is not None and \
                prog is not None and \
                getattr(prog, "_eval_builder", None) is not None and \
                metrics_ok and batch0 is not None and div and \
                b0 is not None and b0 % div == 0 and b0 >= div:
            # evaluate under the TRAINING shardings — no host gather of
            # params, no single-device replication of a model that only
            # fits sharded (pp/tp/ZeRO-3 scale). Metric states come from
            # the step's returned outputs (batch-sized transfer only);
            # pipeline programs (no outs) and partial final batches fall
            # through to the synced path.
            labels_j = _to_jax(labels)
            res = prog.eval_step(*_to_jax(inputs), *labels_j)
            if getattr(prog, "_eval_returns_outs", False):
                loss, outs = res
                if self._metrics:
                    self._update_metrics(jax.device_get(outs), labels_j)
            else:
                loss = res
            return [float(jax.device_get(loss))]
        self._sync_dist_if_dirty()     # eval on the TRAINED params
        if self._jit_eval is None:
            self._jit_eval = self._build_eval_step()
        if self._jit_step is not None:
            params, state = self._params, self._state
        else:
            params, state = self._split_tree()
        inputs, labels = _to_jax(inputs), _to_jax(labels)
        loss, outs = self._jit_eval(params, state, inputs, labels)
        self._update_metrics(outs, labels)
        return [float(jax.device_get(loss))] if loss is not None else []

    def predict_batch(self, inputs):
        self.network.eval()
        self._sync_dist_if_dirty()
        if self._jit_eval is None:
            self._jit_eval = self._build_eval_step()
        if self._jit_step is not None:
            params, state = self._params, self._state
        else:
            params, state = self._split_tree()
        _, outs = self._jit_eval({**params}, state, _to_jax(inputs), [])
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        return [np.asarray(jax.device_get(o)) for o in outs]

    def _update_metrics(self, outs, labels):
        if not self._metrics:
            return
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        pred = Tensor(outs[0])
        lbls = [Tensor(l) for l in labels]
        for m in self._metrics:
            res = m.compute(pred, *lbls)
            res = res if isinstance(res, (list, tuple)) else [res]
            m.update(*[np.asarray(r._data if isinstance(r, Tensor) else r)
                       for r in res])

    # ------------------------------------------------------------------
    def _make_loader(self, data, batch_size, shuffle, drop_last=False,
                     num_workers=0):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, prefetch_device=True):
        """Train loop with callbacks (reference fit hapi/model.py:1299).

        TPU-grade loop discipline: batches are device_put ahead of compute
        by a background thread (prefetch_device; reference
        operators/reader/buffered_reader.cc) and the per-batch loss stays
        on device until a callback/log actually reads it, so the host
        never blocks the dispatch pipeline between steps."""
        train_loader = self._make_loader(train_data, batch_size, shuffle,
                                         drop_last, num_workers)
        eval_loader = self._make_loader(eval_data, batch_size, False)
        n_acc = max(int(accumulate_grad_batches), 1)
        if n_acc != self._grad_accum_n:
            self._grad_accum_n = n_acc
            self._jit_grad = self._jit_apply = None  # apply step captures n
            self._accum_grads, self._accum_count = None, 0

        metric_names = ["loss"]
        for m in self._metrics:
            n = m.name()
            metric_names += list(n) if isinstance(n, (list, tuple)) else [n]
        cbks = cbks_mod.config_callbacks(
            callbacks, model=self, epochs=epochs,
            steps=self._len_or_none(train_loader), verbose=verbose,
            log_freq=log_freq, save_freq=save_freq, save_dir=save_dir,
            metrics=metric_names)

        cbks.on_begin("train")
        self.stop_training = False
        logs = {}
        try:
            self._fit_epochs(epochs, train_loader, eval_loader, eval_freq,
                             batch_size, num_iters, prefetch_device, cbks,
                             logs)
        finally:
            # hand the user back a live Layer even on Ctrl-C / callback
            # raise: the plain-path jitted step donated the layer's OWN
            # buffers on step 1, so without this the network's Tensors
            # reference deleted arrays. The strategy path device_put-
            # COPIES at compile (tensors stay valid, just stale) and
            # keeps the deferred write_back on eval/save — a full host
            # gather per fit() costs seconds on big models.
            if self._jit_step is not None:
                self._write_back(self._params, self._state)
        return self

    def _fit_epochs(self, epochs, train_loader, eval_loader, eval_freq,
                    batch_size, num_iters, prefetch_device, cbks, logs):
        from ..jit import async_pipeline as _apipe
        window = _apipe.async_steps()
        # window 0: synchronous stepping (fetch the loss every step) —
        # the bit-identical reference for the async path. window >= 1:
        # keep up to that many steps in flight, block_until_ready on the
        # oldest ticket for backpressure, fetch metrics lazily.
        pipeline = (_apipe.AsyncStepPipeline(window, label="hapi.fit")
                    if window >= 1 else None)
        self._async_pipeline = pipeline
        global_step = 0
        try:
            self._fit_epoch_loop(epochs, train_loader, eval_loader,
                                 eval_freq, batch_size, num_iters,
                                 prefetch_device, cbks, logs, pipeline,
                                 global_step)
        finally:
            # the stall watchdog must not outlive the fit that owns it
            if pipeline is not None:
                pipeline.close()

    def _fit_epoch_loop(self, epochs, train_loader, eval_loader, eval_freq,
                        batch_size, num_iters, prefetch_device, cbks, logs,
                        pipeline, global_step):
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            self._reset_metrics()
            it = train_loader
            if prefetch_device:
                from ..io.dataloader import device_prefetch
                # strategy path: place batches directly onto the step's
                # data sharding (known once the first batch has compiled;
                # epoch 0 falls back to default placement). put_batch
                # additionally applies the step's host-side preproc
                # (pipeline microbatching) off the critical path.
                prog = getattr(self, "_dist_prog", None)
                sh = getattr(prog, "data_sharding", None)
                place = getattr(prog, "put_batch", None)
                it = device_prefetch(iter(train_loader), sharding=sh,
                                     place=place)
            it = iter(it)
            step = 0
            try:
                while True:
                    t0 = time.perf_counter()
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                    collate_s = time.perf_counter() - t0
                    cbks.on_batch_begin("train", step, logs)
                    ins, lbls = self._split_batch(batch)
                    t1 = time.perf_counter()
                    losses = self.train_batch(ins, lbls,
                                              sync=pipeline is None)
                    dispatch_s = time.perf_counter() - t1
                    if pipeline is not None and losses:
                        pipeline.submit(losses[0], global_step,
                                        collate_s=collate_s,
                                        dispatch_s=dispatch_s)
                    logs = self._step_logs(losses, step, batch_size)
                    cbks.on_batch_end("train", step, logs)
                    step += 1
                    global_step += 1
                    if num_iters is not None and global_step >= num_iters:
                        self.stop_training = True
                        break
            finally:
                # retire outstanding tickets before eval/save callbacks
                # touch the params, and surface any deferred step failure
                # (AsyncStepError names the poisoned step) inside fit
                if pipeline is not None:
                    pipeline.drain()
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0,
                                          _inside_fit=cbks)
                logs.update({"eval_" + k: v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
        cbks.on_end("train", logs)

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, _inside_fit=None):
        loader = self._make_loader(eval_data, batch_size, False,
                                   num_workers=num_workers)
        self._reset_metrics()
        losses_sum, n = 0.0, 0
        cbks = _inside_fit
        if cbks is None and (callbacks or verbose):
            cbks = cbks_mod.config_callbacks(
                callbacks, model=self, verbose=verbose, log_freq=log_freq,
                steps=self._len_or_none(loader), mode="eval")
        if cbks:
            cbks.on_begin("eval")
        for step, batch in enumerate(loader):
            ins, lbls = self._split_batch(batch)
            losses = self.eval_batch(ins, lbls)
            if losses:
                losses_sum += losses[0]
                n += 1
        logs = {}
        if n:
            logs["loss"] = losses_sum / n
        for m in self._metrics:
            logs.update(self._metric_items(m))
        if cbks:
            cbks.on_end("eval", logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        loader = self._make_loader(test_data, batch_size, False,
                                   num_workers=num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, has_labels=False)
            outputs.append(self.predict_batch(ins))
        # transpose: list-of-batches -> per-output list
        n_out = len(outputs[0]) if outputs else 0
        per_out = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            per_out = [np.concatenate(o, axis=0) for o in per_out]
        return per_out

    # ------------------------------------------------------------------
    def _inputs_spec(self):
        """InputSpec list for inference export (Model(net, inputs=...))."""
        from ..static import InputSpec
        if self._inputs is None:
            raise ValueError(
                "Model.save(training=False) needs the Model constructed "
                "with inputs=[InputSpec(...)] so the exported program's "
                "signature is known")
        out = []
        for s in _as_list(self._inputs):
            if isinstance(s, InputSpec):
                out.append(s)
            else:
                out.append(InputSpec(tuple(s.shape), str(s.dtype)))
        return out

    # ------------------------------------------------------------------
    def _split_batch(self, batch, has_labels=True):
        batch = batch if isinstance(batch, (list, tuple)) else [batch]
        if self._inputs is not None:
            n_in = len(_as_list(self._inputs))
            ins = list(batch[:n_in])
            lbls = list(batch[n_in:]) if has_labels else []
            return ins, lbls
        # no input spec: (x, y) convention — trailing element is the label,
        # dropped (not fed to the network) in predict mode
        n_lbl = 1 if len(batch) > 1 else 0
        if n_lbl == 0:
            return list(batch), []
        return list(batch[:-n_lbl]), \
            (list(batch[-n_lbl:]) if has_labels else [])

    @staticmethod
    def _metric_items(m):
        """paddle Metric.name()/accumulate() may return scalars or lists
        (Accuracy with multiple topk)."""
        names = m.name()
        vals = m.accumulate()
        names = names if isinstance(names, (list, tuple)) else [names]
        vals = vals if isinstance(vals, (list, tuple)) else [vals]
        return list(zip(names, vals))

    def _step_logs(self, losses, step, batch_size):
        logs = {"loss": losses[0] if losses else 0.0, "step": step,
                "batch_size": batch_size}
        # the strategy training step computes only the loss — metric
        # states never update during fit there, so reporting
        # accumulate() would print frozen zeros as if they were live
        if getattr(self, "_strategy", None) is None:
            for m in self._metrics:
                logs.update(self._metric_items(m))
        return logs

    def _reset_metrics(self):
        for m in self._metrics:
            m.reset()

    @staticmethod
    def _len_or_none(loader):
        try:
            return len(loader)
        except Exception:
            return None

    # ------------------------------------------------------------------
    def _sync_network(self):
        """Write jitted-step params back into the Layer tree."""
        if getattr(self, "_dist_prog", None) is not None:
            self._dist_prog.write_back()
        if self._jit_step is not None:
            self._write_back(self._params, self._state)

    def save(self, path, training=True):
        """training=True: checkpoint (state dict + optimizer slots).
        training=False: inference export — serialized StableHLO + params
        via paddle_tpu.jit.save, loadable without the model class
        (reference Model.save hapi/model.py -> save_inference_model)."""
        self._sync_network()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        if not training:
            from .. import jit as jit_mod
            spec = self._inputs_spec()
            jit_mod.save(self.network, path, input_spec=spec)
            return
        from ..framework import save as fsave
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            opt_sd = self._optimizer.state_dict()
            if getattr(self, "_dist_prog", None) is not None:
                opt_sd["functional_state"] = jax.device_get(
                    self._dist_prog.opt_state)
            elif self._jit_step is not None:
                opt_sd["functional_state"] = jax.device_get(self._opt_state)
            with open(path + ".pdopt", "wb") as f:
                pickle.dump(opt_sd, f, protocol=4)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework import load as fload
        sd = fload(path + ".pdparams")
        self.network.set_state_dict(sd)
        self._invalidate()
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            with open(path + ".pdopt", "rb") as f:
                opt_sd = pickle.load(f)
            # functional slots (Adam moments etc.) re-seed the next jit step
            self._restored_opt_state = opt_sd.pop("functional_state", None)
            self._optimizer.set_state_dict(opt_sd)
        return self

    def parameters(self, *a, **k):
        return self.network.parameters(*a, **k)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)
