"""Training callbacks (reference: python/paddle/hapi/callbacks.py —
ProgBarLogger, ModelCheckpoint, LRScheduler, EarlyStopping, VisualDL;
MetricsLogger is the observability-layer addition — periodic JSONL
training telemetry, docs/observability.md)."""
from __future__ import annotations

import json
import numbers
import os
import sys
import time
from typing import List, Optional


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    # lifecycle hooks -----------------------------------------------------
    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda l=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda l=None: None)(logs)

    def on_batch_begin(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_begin", lambda s, l=None: None)(
            step, logs)

    def on_batch_end(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_end", lambda s, l=None: None)(
            step, logs)

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    # per-mode defaults (subclasses override what they need)
    def on_train_begin(self, logs=None): pass
    def on_train_end(self, logs=None): pass
    def on_eval_begin(self, logs=None): pass
    def on_eval_end(self, logs=None): pass
    def on_predict_begin(self, logs=None): pass
    def on_predict_end(self, logs=None): pass
    def on_train_batch_begin(self, step, logs=None): pass
    def on_train_batch_end(self, step, logs=None): pass
    def on_eval_batch_begin(self, step, logs=None): pass
    def on_eval_batch_end(self, step, logs=None): pass


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = callbacks

    def __iter__(self):
        return iter(self.callbacks)

    def _call(self, name, *args):
        for cb in self.callbacks:
            getattr(cb, name)(*args)

    def on_begin(self, mode, logs=None):
        self._call("on_begin", mode, logs)

    def on_end(self, mode, logs=None):
        self._call("on_end", mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call("on_batch_begin", mode, step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call("on_batch_end", mode, step, logs)


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks) if callbacks else []
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    lst = CallbackList(cbks)
    for cb in cbks:
        cb.set_model(model)
        cb.set_params({"batch_size": batch_size, "epochs": epochs,
                       "steps": steps, "verbose": verbose,
                       "save_dir": save_dir,
                       "metrics": metrics or ["loss"]})
    return lst


class ProgBarLogger(Callback):
    """Console progress logging (hapi/callbacks.py ProgBarLogger)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self._t0 = time.time()
        self.epochs = self.params.get("epochs")

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._step = 0
        self._epoch_t0 = time.time()
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")

    def _fmt(self, logs):
        parts = []
        for k in self.params.get("metrics", []):
            if k in (logs or {}):
                v = logs[k]
                parts.append(f"{k}: {v:.4f}" if isinstance(
                    v, numbers.Number) else f"{k}: {v}")
        return " - ".join(parts)

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self.verbose == 2 and self._step % self.log_freq == 0:
            steps = self.params.get("steps")
            # formatting logs forces the async loss fetch (the value is a
            # lazy on-device handle under PADDLE_TPU_ASYNC_STEPS); a
            # coarse log_freq keeps the steps between log points
            # free-running, and throughput here is measured over that
            # whole window, not the (host-blocked) log step alone
            dt = time.time() - self._epoch_t0
            rate = f" - {self._step / dt:.1f} steps/s" if dt > 0 else ""
            print(f"step {self._step}/{steps or '?'} - "
                  f"{self._fmt(logs)}{rate}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - getattr(self, "_epoch_t0", self._t0)
            rate = f" - {self._step / dt:.1f} steps/s" \
                if dt > 0 and self._step else ""
            print(f"epoch {epoch + 1} done ({dt:.1f}s) - "
                  f"{self._fmt(logs)}{rate}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print("Eval - " + " - ".join(
                f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                if isinstance(v, numbers.Number)))


class ModelCheckpoint(Callback):
    """Periodic checkpointing with an atomic publish and retention.

    Model.save writes `{path}.pdparams` (+ `.pdopt`); saving straight to
    the final prefix means a crash mid-write leaves a truncated pickle
    under the name a resume would load. Instead each save goes to a
    `.tmp` prefix and rename-publishes — `.pdopt` first, `.pdparams`
    last, so the params file (the one load() requires) only appears once
    its optimizer twin is in place. `keep_last=k` prunes older epoch
    checkpoints ('final'/'best_model' are never pruned). The full
    fsync+checksum protocol lives in io/checkpoint.py
    (docs/fault_tolerance.md); this callback covers the hapi pickle
    format with the same commit-by-rename discipline."""

    def __init__(self, save_freq=1, save_dir=None, keep_last=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir
        self.keep_last = keep_last

    def _atomic_save(self, path):
        tmp = path + ".tmp"
        self.model.save(tmp)
        # publish order: params LAST = commit point
        for ext in (".pdopt", ".pdparams"):
            if os.path.exists(tmp + ext):
                os.replace(tmp + ext, path + ext)

    def _gc(self):
        if not self.keep_last or not os.path.isdir(self.save_dir):
            return
        epochs = sorted({int(f.split(".")[0])
                         for f in os.listdir(self.save_dir)
                         if f.split(".")[0].isdigit()
                         and f.endswith((".pdparams", ".pdopt"))})
        for e in epochs[:-self.keep_last] if len(epochs) > self.keep_last \
                else []:
            for ext in (".pdparams", ".pdopt"):
                p = os.path.join(self.save_dir, f"{e}{ext}")
                if os.path.exists(p):
                    os.unlink(p)

    def on_epoch_end(self, epoch, logs=None):
        if self.model and self.save_dir and (epoch + 1) % self.save_freq == 0:
            self._atomic_save(os.path.join(self.save_dir, f"{epoch}"))
            self._gc()

    def on_train_end(self, logs=None):
        if self.model and self.save_dir:
            self._atomic_save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (by default once per epoch, matching
    the reference's by_epoch=True; per-step via by_step=True)."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch and not by_step

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None) if opt else None

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "max" or (mode == "auto" and ("acc" in monitor
                                                 or "auc" in monitor)):
            self.better = lambda a, b: a > b + self.min_delta
            self.best = -float("inf")
        else:
            self.better = lambda a, b: a < b - self.min_delta
            self.best = float("inf")
        self.wait = 0

    def on_train_begin(self, logs=None):
        # fit(save_dir=...) propagates here via set_params
        self.save_dir = self.params.get("save_dir")

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        # prefer the eval metric: stopping on the last train-batch loss
        # would track noise and never catch overfitting (the reference
        # monitors eval results)
        cur = logs.get("eval_" + self.monitor, logs.get(self.monitor))
        if cur is None:
            return
        if self.better(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.model is not None and \
                    getattr(self, "save_dir", None):
                self.model.save(os.path.join(self.save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait > self.patience:
                if self.model is not None:
                    self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping at epoch {epoch + 1}: "
                          f"best {self.monitor}={self.best:.4f}")


class MetricsLogger(Callback):
    """Periodic machine-readable training telemetry.

    Every ``log_freq`` train batches (and at each epoch end) one JSON
    line goes to ``path`` (append; default stderr): monotonic timestamp,
    epoch/step, steps/s over the window, the numeric entries of ``logs``
    (loss, metrics), and — when the model runs the async step pipeline —
    ``host_blocked_s`` / ``in_flight`` / ``steps_submitted`` from
    ``model._async_pipeline.stats()``. Per-device HBM is sampled guarded:
    backends with nothing to report contribute nothing and never raise.

    The line format matches the serve-side span JSONL (one self-contained
    object per line) so the same tooling tails both."""

    def __init__(self, log_freq: int = 50, path: Optional[str] = None,
                 hbm: bool = True):
        super().__init__()
        self.log_freq = max(int(log_freq), 1)
        self.path = path
        self.hbm = hbm
        self._f = None
        self._step = 0
        self._win_t0 = None
        self._win_step0 = 0

    def _emit(self, payload: dict):
        line = json.dumps(payload)
        if self._f is not None:
            self._f.write(line + "\n")
            self._f.flush()
        else:
            print("TRAIN_METRICS " + line, file=sys.stderr, flush=True)

    def on_train_begin(self, logs=None):
        if self.path:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._f = open(self.path, "a")
        self._step = 0
        self._win_t0 = time.monotonic()
        self._win_step0 = 0
        self._epoch = 0

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def _payload(self, logs, event):
        now = time.monotonic()
        dt = now - (self._win_t0 or now)
        steps = self._step - self._win_step0
        payload = {k: float(v) for k, v in (logs or {}).items()
                   if isinstance(v, numbers.Number)}
        # structural fields win over same-named log entries (hapi logs
        # carry their own "step": the in-epoch index, not ours)
        payload.update(
            ts_monotonic=round(now, 3),
            event=event,
            epoch=self._epoch,
            step=self._step,
            steps_per_s=round(steps / dt, 3) if dt > 0 and steps else 0.0)
        pipe = getattr(self.model, "_async_pipeline", None)
        if pipe is not None:
            try:
                payload.update(pipe.stats())
            except Exception:
                pass
        if self.hbm:
            try:
                from ..core import monitor
                hbm = {dev: st["bytes_in_use"]
                       for dev, st in
                       monitor.all_device_memory_stats().items()
                       if st.get("bytes_in_use") is not None}
                if hbm:
                    payload["hbm_bytes_in_use"] = hbm
            except Exception:
                pass
        self._win_t0 = now
        self._win_step0 = self._step
        return payload

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self._step % self.log_freq == 0:
            self._emit(self._payload(logs, "step"))

    def on_epoch_end(self, epoch, logs=None):
        self._emit(self._payload(logs, "epoch_end"))

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()
            self._f = None


class VisualDL(Callback):
    """Scalar logger writing TSV (the reference logs to the external VisualDL
    package; zero-dependency equivalent keeping the same callback surface)."""

    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._f = None
        self._step = 0

    def on_train_begin(self, logs=None):
        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(os.path.join(self.log_dir, "scalars.tsv"), "a")
        self._f.write(f"# run {time.strftime('%Y-%m-%dT%H:%M:%S')}\n")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                self._f.write(f"{self._step}\t{k}\t{v}\n")
        self._f.flush()  # survive crashes mid-training

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()
