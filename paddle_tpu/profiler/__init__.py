"""Profiler: host event annotation + aggregated tables + device tracing.

Reference: RAII RecordEvent pushed at every op (platform/profiler.h:127,
tracer.cc:136), EnableProfiler/DisableProfiler building aggregated tables
and a chrome trace (profiler.h:210, platform/profiler.proto), CUPTI
DeviceTracer correlating kernel timestamps (device_tracer.h:43), python
surface fluid/profiler.py.

TPU-native mapping: device-side timing belongs to XLA/libtpu — jax
profiler traces (XPlane) already carry per-fusion device timelines, so
`start_trace/stop_trace` delegate there (view in TensorBoard/xprof).
Host-side RecordEvent keeps the reference's annotation API: it feeds BOTH
the in-process aggregation table (summary() below) and
jax.profiler.TraceAnnotation so host spans land on the XPlane timeline
next to the device rows. Per-op auto-annotation hooks into the eager
dispatcher when the profiler is on.
"""
from __future__ import annotations

import contextlib
import math
import threading
import time
from typing import Dict, List, Optional

import jax

__all__ = ["RecordEvent", "start_profiler", "stop_profiler", "profiler",
           "start_trace", "stop_trace", "is_profiling", "summary",
           "record_compile", "compile_events", "reset_compile_events",
           "record_step", "step_timeline", "reset_step_timeline",
           "step_timeline_summary",
           "record_serve_batch", "record_serve_request",
           "record_serve_requests", "record_serve_error",
           "serve_stats", "reset_serve_stats"]

_lock = threading.Lock()
_events: List[tuple] = []      # (name, start, dur, thread_id)
_compiles: List[dict] = []     # {label, compile_s, cache}
_steps: List[dict] = []        # per-step timeline segments
_STEP_CAP = 100_000            # bound memory on very long runs
_enabled = False


def is_profiling() -> bool:
    return _enabled


class RecordEvent:
    """RAII/contextmanager/decorator annotation (profiler.h:127 analog).

        with profiler.RecordEvent("data_load"):
            ...
    Active even when only jax tracing is on (TraceAnnotation); the table
    row is recorded only while the host profiler is enabled."""

    def __init__(self, name: str):
        self.name = name
        self._ann = None
        self._t0 = None

    def __enter__(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0
        self._ann.__exit__(*exc)
        if _enabled:
            with _lock:
                _events.append((self.name, self._t0, dur,
                                threading.get_ident()))
        return False

    def __call__(self, fn):
        def wrapped(*a, **k):
            with RecordEvent(self.name):
                return fn(*a, **k)
        return wrapped


def _op_hook(op_name):
    """Eager-dispatcher hook: annotate each op while profiling."""
    return RecordEvent(f"op::{op_name}") if _enabled else None


from ..core import tensor as _tensor_mod

_tensor_mod._profiler_hook[0] = _op_hook


def record_compile(label: str, seconds: float, cache: str = "off"):
    """Record one XLA compile (jit/compile_cache.aot_compile feeds this).

    Always collected — compiles are rare and the bench needs them even
    with the host profiler off; also lands in the event table when the
    profiler IS on."""
    with _lock:
        _compiles.append({"label": label, "compile_s": float(seconds),
                          "cache": cache})
        if _enabled:
            _events.append((f"compile::{label}",
                            time.perf_counter() - seconds, seconds,
                            threading.get_ident()))


def compile_events() -> List[dict]:
    """Compiles recorded so far: [{label, compile_s, cache}, ...]."""
    with _lock:
        return [dict(e) for e in _compiles]


def reset_compile_events():
    with _lock:
        _compiles.clear()


def record_step(step: int, **segments):
    """Record one train step's host/device timeline segments.

    Fed by jit.async_pipeline at ticket-retire time with
    ``collate_s`` (host wait on the input iterator), ``dispatch_s``
    (host time spent launching the step), ``compute_s`` (submit-to-ready
    device latency) and ``fetch_s`` (host wall-clock actually *blocked*
    waiting for the result), plus ``in_flight``.  Overlap is proven when
    ``collate_s + dispatch_s + fetch_s`` (the dispatch gap the host pays)
    is well under ``compute_s`` (the device step time).  Always
    collected, like compiles — bench.py aggregates these into its
    ``host_blocked_s`` / ``steps_in_flight`` JSON fields."""
    with _lock:
        _steps.append({"step": int(step), **segments})
        if len(_steps) > _STEP_CAP:
            del _steps[: len(_steps) - _STEP_CAP]
        if _enabled:
            now = time.perf_counter()
            for seg in ("collate_s", "dispatch_s", "compute_s", "fetch_s"):
                if segments.get(seg):
                    _events.append((f"step::{seg[:-2]}", now,
                                    float(segments[seg]),
                                    threading.get_ident()))


def step_timeline() -> List[dict]:
    """Per-step timeline recorded so far:
    [{step, collate_s, dispatch_s, compute_s, fetch_s, in_flight}, ...]"""
    with _lock:
        return [dict(e) for e in _steps]


def reset_step_timeline():
    with _lock:
        _steps.clear()


def step_timeline_summary() -> dict:
    """Aggregate of the step timeline for bench/report JSON."""
    tl = step_timeline()
    if not tl:
        return {"steps": 0, "host_blocked_s": 0.0, "steps_in_flight": 0,
                "dispatch_gap_s": 0.0, "device_step_s": 0.0}
    n = len(tl)
    host_blocked = sum(e.get("fetch_s", 0.0) for e in tl)
    gap = sum(e.get("collate_s", 0.0) + e.get("dispatch_s", 0.0)
              + e.get("fetch_s", 0.0) for e in tl)
    dev = sum(e.get("compute_s", 0.0) for e in tl)
    return {
        "steps": n,
        "host_blocked_s": round(host_blocked, 6),
        "steps_in_flight": max(int(e.get("in_flight", 1)) for e in tl),
        # mean host-paid gap per step vs mean device step time: overlap
        # is working when dispatch_gap_s < device_step_s
        "dispatch_gap_s": round(gap / n, 6),
        "device_step_s": round(dev / n, 6),
    }


# ---------------------------------------------------------------------------
# serving counters (inference.batching.DynamicBatcher feeds these)
# ---------------------------------------------------------------------------

_LAT_CAP = 100_000             # bound latency-sample memory on long runs


def _serve_zero() -> dict:
    return {"requests": 0, "errors": 0, "batches": 0,
            "rows": 0, "capacity": 0, "real_elems": 0, "padded_elems": 0,
            "queue_depth_max": 0, "lat": [], "t0": None, "t1": None}


_serve = _serve_zero()


def record_serve_batch(rows: int, capacity: int, real_elems: int,
                       padded_elems: int, queue_depth: int = 0):
    """Record one dispatched inference batch: ``rows`` real request rows
    packed into a ``capacity``-row bucket, ``real_elems``/``padded_elems``
    element counts before/after shape-bucket padding, and the request
    queue depth observed at dispatch. Always collected (like compiles):
    the serve stats line and benchmarks/serve_bench.py read these with
    the host profiler off."""
    with _lock:
        _serve["batches"] += 1
        _serve["rows"] += int(rows)
        _serve["capacity"] += int(capacity)
        _serve["real_elems"] += int(real_elems)
        _serve["padded_elems"] += int(padded_elems)
        _serve["queue_depth_max"] = max(_serve["queue_depth_max"],
                                        int(queue_depth))


def record_serve_request(latency_s: float):
    """Record one successfully answered request (enqueue-to-result wall
    clock). Timestamps of the first/last resolution bound the reqs/s
    window in :func:`serve_stats`."""
    record_serve_requests((latency_s,))


def record_serve_requests(latencies_s):
    """Batch form of :func:`record_serve_request` — one lock acquisition
    for a whole dispatched batch's resolutions."""
    now = time.perf_counter()
    with _lock:
        _serve["requests"] += len(latencies_s)
        _serve["lat"].extend(float(v) for v in latencies_s)
        if len(_serve["lat"]) > _LAT_CAP:
            del _serve["lat"][: len(_serve["lat"]) - _LAT_CAP]
        if _serve["t0"] is None:
            _serve["t0"] = now
        _serve["t1"] = now


def record_serve_error():
    """Record one request that resolved with an error (its latency is not
    mixed into the percentiles)."""
    with _lock:
        _serve["errors"] += 1


def _pctile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(math.ceil(q * len(sorted_vals))) - 1))
    return sorted_vals[k]


def serve_stats() -> dict:
    """Aggregate serving counters: request/batch totals, reqs_per_s,
    batch_occupancy (real rows / padded bucket rows), padding_waste
    (fraction of dispatched elements that were padding), queue_depth_max,
    compile_count (all compiles recorded via record_compile) and
    p50/p95/p99 request latency in ms."""
    with _lock:
        s = {k: v for k, v in _serve.items() if k != "lat"}
        lat = sorted(_serve["lat"])
        n_compiles = len(_compiles)
    dur = (s["t1"] - s["t0"]) if s["t0"] is not None else 0.0
    return {
        "requests": s["requests"],
        "errors": s["errors"],
        "batches": s["batches"],
        "reqs_per_s": round(s["requests"] / dur, 2) if dur > 0 else 0.0,
        "batch_occupancy": round(s["rows"] / s["capacity"], 4)
        if s["capacity"] else 0.0,
        "padding_waste": round(1.0 - s["real_elems"] / s["padded_elems"], 4)
        if s["padded_elems"] else 0.0,
        "queue_depth_max": s["queue_depth_max"],
        "compile_count": n_compiles,
        "p50_latency_ms": round(_pctile(lat, 0.50) * 1e3, 3),
        "p95_latency_ms": round(_pctile(lat, 0.95) * 1e3, 3),
        "p99_latency_ms": round(_pctile(lat, 0.99) * 1e3, 3),
    }


def reset_serve_stats():
    global _serve
    with _lock:
        _serve = _serve_zero()


def start_profiler(state: str = "All", tracer_option: str = "Default"):
    """fluid/profiler.py surface; `state`/`tracer_option` kept for parity
    (host events always; device events come from start_trace/XPlane)."""
    global _enabled
    with _lock:
        _events.clear()
    _enabled = True


def stop_profiler(sorted_key: str = "total", profile_path: Optional[str] = None,
                  print_table: bool = True):
    global _enabled
    _enabled = False
    table = summary(sorted_key)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(table)
    if print_table:
        print(table)
    return table


def summary(sorted_key: str = "total") -> str:
    """Aggregated event table (EnableProfiler table analog)."""
    with _lock:
        events = list(_events)
    agg: Dict[str, List[float]] = {}
    for name, _, dur, _ in events:
        agg.setdefault(name, []).append(dur)
    keyfn = {"total": lambda kv: -sum(kv[1]),
             "max": lambda kv: -max(kv[1]),
             "min": lambda kv: -min(kv[1]),
             "calls": lambda kv: -len(kv[1])}.get(
        sorted_key, lambda kv: -sum(kv[1]))
    rows = sorted(agg.items(), key=keyfn)
    total_all = sum(sum(v) for v in agg.values()) or 1e-12
    lines = [f"{'Event':<40s} {'Calls':>7s} {'Total(ms)':>10s} "
             f"{'Avg(ms)':>9s} {'Min(ms)':>9s} {'Max(ms)':>9s} {'Ratio':>7s}"]
    for name, durs in rows:
        t = sum(durs)
        lines.append(
            f"{name[:40]:<40s} {len(durs):>7d} {t * 1e3:>10.3f} "
            f"{t / len(durs) * 1e3:>9.3f} {min(durs) * 1e3:>9.3f} "
            f"{max(durs) * 1e3:>9.3f} {t / total_all:>6.1%}")
    return "\n".join(lines)


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None):
    """`with profiler.profiler(...):` — fluid/profiler.py parity."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


# ---------------------------------------------------------------------------
# device tracing (XPlane; view with TensorBoard profile plugin / xprof)
# ---------------------------------------------------------------------------

def start_trace(log_dir: str):
    """DeviceTracer analog: libtpu/XLA device timelines via jax.profiler."""
    jax.profiler.start_trace(log_dir)


def stop_trace():
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(log_dir: str):
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()
