"""Profiler: host event annotation + aggregated tables + device tracing.

Reference: RAII RecordEvent pushed at every op (platform/profiler.h:127,
tracer.cc:136), EnableProfiler/DisableProfiler building aggregated tables
and a chrome trace (profiler.h:210, platform/profiler.proto), CUPTI
DeviceTracer correlating kernel timestamps (device_tracer.h:43), python
surface fluid/profiler.py.

TPU-native mapping: device-side timing belongs to XLA/libtpu — jax
profiler traces (XPlane) already carry per-fusion device timelines, so
`start_trace/stop_trace` delegate there (view in TensorBoard/xprof).
Host-side RecordEvent keeps the reference's annotation API: it feeds BOTH
the in-process aggregation table (summary() below) and
jax.profiler.TraceAnnotation so host spans land on the XPlane timeline
next to the device rows. Per-op auto-annotation hooks into the eager
dispatcher when the profiler is on.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List, Optional

import jax

from ..observability import metrics as _metrics

__all__ = ["RecordEvent", "start_profiler", "stop_profiler", "profiler",
           "start_trace", "stop_trace", "is_profiling", "summary",
           "record_compile", "compile_events", "reset_compile_events",
           "record_step", "step_timeline", "reset_step_timeline",
           "step_timeline_summary",
           "record_serve_batch", "record_serve_request",
           "record_serve_requests", "record_serve_error",
           "serve_stats", "reset_serve_stats"]

_lock = threading.Lock()
_events: List[tuple] = []      # (name, start, dur, thread_id)
_compiles: List[dict] = []     # {label, compile_s, cache}
_steps: List[dict] = []        # per-step timeline segments
_STEP_CAP = 100_000            # bound memory on very long runs
_enabled = False

# ---------------------------------------------------------------------------
# registry-backed aggregates: the observability registry is the single
# store for every scalar counter below (docs/observability.md catalog);
# this module keeps only the list-shaped views (event table, compile
# labels, step timeline) plus the reqs/s timestamp window.
# ---------------------------------------------------------------------------
_SRV_REQS = _metrics.counter(
    "paddle_tpu_serve_requests_total",
    "Requests answered successfully by the serving engine.")
_SRV_ERRS = _metrics.counter(
    "paddle_tpu_serve_errors_total",
    "Requests that resolved with an error.")
_SRV_BATCHES = _metrics.counter(
    "paddle_tpu_serve_batches_total",
    "Batches dispatched by the DynamicBatcher.")
_SRV_ROWS = _metrics.counter(
    "paddle_tpu_serve_batch_rows_total",
    "Real request rows packed into dispatched batches.")
_SRV_CAP = _metrics.counter(
    "paddle_tpu_serve_batch_capacity_rows_total",
    "Bucket-capacity rows dispatched (rows/capacity = occupancy).")
_SRV_REAL = _metrics.counter(
    "paddle_tpu_serve_real_elements_total",
    "Tensor elements dispatched before shape-bucket padding.")
_SRV_PADDED = _metrics.counter(
    "paddle_tpu_serve_padded_elements_total",
    "Tensor elements dispatched after shape-bucket padding "
    "(1 - real/padded = padding waste).")
_SRV_QDEPTH = _metrics.gauge(
    "paddle_tpu_serve_queue_depth",
    "Request queue depth observed at the most recent dispatch.")
_SRV_QMAX = _metrics.gauge(
    "paddle_tpu_serve_queue_depth_max",
    "Deepest the request queue has been since the last stats reset.")
_SRV_LAT = _metrics.histogram(
    "paddle_tpu_serve_request_latency_seconds",
    "Enqueue-to-result wall clock per successfully answered request.",
    sample_cap=100_000)        # reservoir: exact p50/p95/p99 below
_COMPILE_N = _metrics.counter(
    "paddle_tpu_compile_total",
    "Explicit XLA compiles recorded via profiler.record_compile.")
_COMPILE_S = _metrics.counter(
    "paddle_tpu_compile_seconds_total",
    "Seconds spent in explicit XLA compiles.")
_STEP_N = _metrics.counter(
    "paddle_tpu_train_steps_total",
    "Train steps retired through the async step pipeline.")
_STEP_BLOCKED_S = _metrics.counter(
    "paddle_tpu_train_host_blocked_seconds_total",
    "Host wall clock blocked waiting on device step results.")
_STEP_INFLIGHT = _metrics.gauge(
    "paddle_tpu_train_steps_in_flight",
    "Dispatched-but-unfetched steps at the last retirement.")


def is_profiling() -> bool:
    with _lock:
        return _enabled


class RecordEvent:
    """RAII/contextmanager/decorator annotation (profiler.h:127 analog).

        with profiler.RecordEvent("data_load"):
            ...
    Active even when only jax tracing is on (TraceAnnotation); the table
    row is recorded only while the host profiler is enabled."""

    def __init__(self, name: str):
        self.name = name
        self._ann = None
        self._t0 = None

    def __enter__(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0
        self._ann.__exit__(*exc)
        # read _enabled INSIDE the lock: stop_profiler() flips it under
        # the same lock, so an exit racing a disable either lands in the
        # table or cleanly doesn't — never appends to a list summary()
        # is snapshotting
        with _lock:
            if _enabled:
                _events.append((self.name, self._t0, dur,
                                threading.get_ident()))
        return False

    def __call__(self, fn):
        def wrapped(*a, **k):
            with RecordEvent(self.name):
                return fn(*a, **k)
        return wrapped


def _op_hook(op_name):
    """Eager-dispatcher hook: annotate each op while profiling."""
    with _lock:
        enabled = _enabled
    return RecordEvent(f"op::{op_name}") if enabled else None


from ..core import tensor as _tensor_mod

_tensor_mod._profiler_hook[0] = _op_hook


def record_compile(label: str, seconds: float, cache: str = "off"):
    """Record one XLA compile (jit/compile_cache.aot_compile feeds this).

    Always collected — compiles are rare and the bench needs them even
    with the host profiler off; also lands in the event table when the
    profiler IS on."""
    with _lock:
        _compiles.append({"label": label, "compile_s": float(seconds),
                          "cache": cache})
        if _enabled:
            _events.append((f"compile::{label}",
                            time.perf_counter() - seconds, seconds,
                            threading.get_ident()))
    _COMPILE_N.inc()
    _COMPILE_S.inc(max(float(seconds), 0.0))


def compile_events() -> List[dict]:
    """Compiles recorded so far: [{label, compile_s, cache}, ...]."""
    with _lock:
        return [dict(e) for e in _compiles]


def reset_compile_events():
    with _lock:
        _compiles.clear()


def record_step(step: int, **segments):
    """Record one train step's host/device timeline segments.

    Fed by jit.async_pipeline at ticket-retire time with
    ``collate_s`` (host wait on the input iterator), ``dispatch_s``
    (host time spent launching the step), ``compute_s`` (submit-to-ready
    device latency) and ``fetch_s`` (host wall-clock actually *blocked*
    waiting for the result), plus ``in_flight``.  Overlap is proven when
    ``collate_s + dispatch_s + fetch_s`` (the dispatch gap the host pays)
    is well under ``compute_s`` (the device step time).  Always
    collected, like compiles — bench.py aggregates these into its
    ``host_blocked_s`` / ``steps_in_flight`` JSON fields."""
    with _lock:
        _steps.append({"step": int(step), **segments})
        if len(_steps) > _STEP_CAP:
            del _steps[: len(_steps) - _STEP_CAP]
        if _enabled:
            now = time.perf_counter()
            for seg in ("collate_s", "dispatch_s", "compute_s", "fetch_s"):
                if segments.get(seg):
                    _events.append((f"step::{seg[:-2]}", now,
                                    float(segments[seg]),
                                    threading.get_ident()))
    _STEP_N.inc()
    _STEP_BLOCKED_S.inc(max(float(segments.get("fetch_s", 0.0) or 0.0),
                            0.0))
    if segments.get("in_flight") is not None:
        _STEP_INFLIGHT.set(int(segments["in_flight"]))


def step_timeline() -> List[dict]:
    """Per-step timeline recorded so far:
    [{step, collate_s, dispatch_s, compute_s, fetch_s, in_flight}, ...]"""
    with _lock:
        return [dict(e) for e in _steps]


def reset_step_timeline():
    with _lock:
        _steps.clear()


def step_timeline_summary() -> dict:
    """Aggregate of the step timeline for bench/report JSON."""
    tl = step_timeline()
    if not tl:
        return {"steps": 0, "host_blocked_s": 0.0, "steps_in_flight": 0,
                "dispatch_gap_s": 0.0, "device_step_s": 0.0}
    n = len(tl)
    host_blocked = sum(e.get("fetch_s", 0.0) for e in tl)
    gap = sum(e.get("collate_s", 0.0) + e.get("dispatch_s", 0.0)
              + e.get("fetch_s", 0.0) for e in tl)
    dev = sum(e.get("compute_s", 0.0) for e in tl)
    return {
        "steps": n,
        "host_blocked_s": round(host_blocked, 6),
        "steps_in_flight": max(int(e.get("in_flight", 1)) for e in tl),
        # mean host-paid gap per step vs mean device step time: overlap
        # is working when dispatch_gap_s < device_step_s
        "dispatch_gap_s": round(gap / n, 6),
        "device_step_s": round(dev / n, 6),
    }


# ---------------------------------------------------------------------------
# serving counters (inference.batching.DynamicBatcher feeds these)
# ---------------------------------------------------------------------------

# first/last resolution timestamps bounding the reqs/s window
_serve_t = {"t0": None, "t1": None}


def record_serve_batch(rows: int, capacity: int, real_elems: int,
                       padded_elems: int, queue_depth: int = 0):
    """Record one dispatched inference batch: ``rows`` real request rows
    packed into a ``capacity``-row bucket, ``real_elems``/``padded_elems``
    element counts before/after shape-bucket padding, and the request
    queue depth observed at dispatch. Always collected (like compiles):
    the serve stats line and benchmarks/serve_bench.py read these with
    the host profiler off."""
    _SRV_BATCHES.inc()
    _SRV_ROWS.inc(int(rows))
    _SRV_CAP.inc(int(capacity))
    _SRV_REAL.inc(int(real_elems))
    _SRV_PADDED.inc(int(padded_elems))
    _SRV_QDEPTH.set(int(queue_depth))
    _SRV_QMAX.set_max(int(queue_depth))


def record_serve_request(latency_s: float):
    """Record one successfully answered request (enqueue-to-result wall
    clock). Timestamps of the first/last resolution bound the reqs/s
    window in :func:`serve_stats`."""
    record_serve_requests((latency_s,))


def record_serve_requests(latencies_s):
    """Batch form of :func:`record_serve_request` — one dispatched
    batch's resolutions in one call."""
    latencies_s = list(latencies_s)
    now = time.perf_counter()
    _SRV_REQS.inc(len(latencies_s))
    for v in latencies_s:
        _SRV_LAT.observe(float(v))
    with _lock:
        if _serve_t["t0"] is None:
            _serve_t["t0"] = now
        _serve_t["t1"] = now


def record_serve_error():
    """Record one request that resolved with an error (its latency is not
    mixed into the percentiles)."""
    _SRV_ERRS.inc()


def serve_stats() -> dict:
    """Aggregate serving counters (read from the observability registry,
    the single backing store): request/batch totals, reqs_per_s,
    batch_occupancy (real rows / padded bucket rows), padding_waste
    (fraction of dispatched elements that were padding), queue_depth_max,
    compile_count (all compiles recorded via record_compile) and
    p50/p95/p99 request latency in ms."""
    with _lock:
        n_compiles = len(_compiles)
        t0, t1 = _serve_t["t0"], _serve_t["t1"]
    requests = int(_SRV_REQS.get())
    rows, cap = _SRV_ROWS.get(), _SRV_CAP.get()
    real, padded = _SRV_REAL.get(), _SRV_PADDED.get()
    # reqs/s window: first-to-last resolution; a single resolution (or
    # one batch) collapses the window to zero, so fall back to
    # time-since-first-resolution — and report null (never a misleading
    # 0.0) if even that is degenerate
    rate = 0.0 if requests == 0 else None
    if t0 is not None and requests:
        dur = t1 - t0
        if dur <= 0:
            dur = time.perf_counter() - t0
        if dur > 0:
            rate = round(requests / dur, 2)
    return {
        "requests": requests,
        "errors": int(_SRV_ERRS.get()),
        "batches": int(_SRV_BATCHES.get()),
        "reqs_per_s": rate,
        "batch_occupancy": round(rows / cap, 4) if cap else 0.0,
        "padding_waste": round(1.0 - real / padded, 4) if padded else 0.0,
        "queue_depth_max": int(_SRV_QMAX.get()),
        "compile_count": n_compiles,
        "p50_latency_ms": round(_SRV_LAT.percentile(0.50) * 1e3, 3),
        "p95_latency_ms": round(_SRV_LAT.percentile(0.95) * 1e3, 3),
        "p99_latency_ms": round(_SRV_LAT.percentile(0.99) * 1e3, 3),
    }


def reset_serve_stats():
    for inst in (_SRV_REQS, _SRV_ERRS, _SRV_BATCHES, _SRV_ROWS, _SRV_CAP,
                 _SRV_REAL, _SRV_PADDED, _SRV_QDEPTH, _SRV_QMAX,
                 _SRV_LAT):
        inst.reset()
    with _lock:
        _serve_t["t0"] = _serve_t["t1"] = None


def start_profiler(state: str = "All", tracer_option: str = "Default"):
    """fluid/profiler.py surface; `state`/`tracer_option` kept for parity
    (host events always; device events come from start_trace/XPlane).
    The enable flip happens under the event-table lock so recorders
    racing the transition see a consistent (flag, table) pair."""
    global _enabled
    with _lock:
        _events.clear()
        _enabled = True


def stop_profiler(sorted_key: str = "total", profile_path: Optional[str] = None,
                  print_table: bool = True):
    global _enabled
    with _lock:
        _enabled = False
    table = summary(sorted_key)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(table)
    if print_table:
        print(table)
    return table


def summary(sorted_key: str = "total") -> str:
    """Aggregated event table (EnableProfiler table analog)."""
    with _lock:
        events = list(_events)
    agg: Dict[str, List[float]] = {}
    for name, _, dur, _ in events:
        agg.setdefault(name, []).append(dur)
    keyfn = {"total": lambda kv: -sum(kv[1]),
             "max": lambda kv: -max(kv[1]),
             "min": lambda kv: -min(kv[1]),
             "calls": lambda kv: -len(kv[1])}.get(
        sorted_key, lambda kv: -sum(kv[1]))
    rows = sorted(agg.items(), key=keyfn)
    total_all = sum(sum(v) for v in agg.values()) or 1e-12
    lines = [f"{'Event':<40s} {'Calls':>7s} {'Total(ms)':>10s} "
             f"{'Avg(ms)':>9s} {'Min(ms)':>9s} {'Max(ms)':>9s} {'Ratio':>7s}"]
    for name, durs in rows:
        t = sum(durs)
        lines.append(
            f"{name[:40]:<40s} {len(durs):>7d} {t * 1e3:>10.3f} "
            f"{t / len(durs) * 1e3:>9.3f} {min(durs) * 1e3:>9.3f} "
            f"{max(durs) * 1e3:>9.3f} {t / total_all:>6.1%}")
    return "\n".join(lines)


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None):
    """`with profiler.profiler(...):` — fluid/profiler.py parity."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


# ---------------------------------------------------------------------------
# device tracing (XPlane; view with TensorBoard profile plugin / xprof)
# ---------------------------------------------------------------------------

def start_trace(log_dir: str):
    """DeviceTracer analog: libtpu/XLA device timelines via jax.profiler."""
    jax.profiler.start_trace(log_dir)


def stop_trace():
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(log_dir: str):
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()
