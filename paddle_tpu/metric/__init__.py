"""Metrics (reference: python/paddle/metric/metrics.py — Metric base,
Accuracy, Precision, Recall, Auc)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        order = np.argsort(-pred, axis=-1)[..., :self.maxk]
        if label.ndim == pred.ndim:  # one-hot or column label
            if label.shape[-1] == 1:
                label = label.squeeze(-1)
            else:
                label = np.argmax(label, axis=-1)
        correct = (order == label[..., None]).astype(np.float32)
        return Tensor(correct)

    def update(self, correct, *args):
        correct = _np(correct)
        accs = []
        for k in self.topk:
            num = correct[..., :k].sum()
            tot = int(np.prod(correct.shape[:-1]))
            self.total[self.topk.index(k)] += num
            self.count[self.topk.index(k)] += tot
            accs.append(num / max(tot, 1))
        return np.array(accs[0] if len(accs) == 1 else accs)

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype(np.int32).reshape(-1)
        labels = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype(np.int32).reshape(-1)
        labels = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Thresholded-histogram AUC (reference: metrics.py Auc / auc_op)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.reshape(-1)
        idx = np.minimum((pos_prob * self._num_thresholds).astype(np.int64),
                         self._num_thresholds - 1)
        for i, lbl in zip(idx, labels):
            if lbl:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds, np.int64)
        self._stat_neg = np.zeros(self._num_thresholds, np.int64)

    def accumulate(self):
        tot_pos = float(self._stat_pos.sum())
        tot_neg = float(self._stat_neg.sum())
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate TPR over FPR from the high-score end
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        trapezoid = getattr(np, "trapezoid", None) or np.trapz
        return float(trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional accuracy (reference: metric/metrics.py accuracy)."""
    import jax.numpy as jnp

    from ..core.tensor import apply

    def f(pred, lbl):
        topk_idx = jnp.argsort(-pred, axis=-1)[..., :k]
        if lbl.ndim == pred.ndim:
            lbl = lbl[..., 0] if lbl.shape[-1] == 1 else jnp.argmax(lbl, -1)
        hit = (topk_idx == lbl[..., None]).any(axis=-1)
        return jnp.mean(hit.astype(jnp.float32))
    return apply(f, input, label, op_name="accuracy")


import sys as _sys

metrics = _sys.modules[__name__]   # reference alias: paddle.metric.metrics
