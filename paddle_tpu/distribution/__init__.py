"""paddle.distribution — Distribution/Uniform/Normal/Categorical.

Reference: /root/reference/python/paddle/distribution.py:41 (Distribution),
:168 (Uniform), :390 (Normal), :640 (Categorical). The reference builds
each method from fluid layer ops (uniform_random, elementwise_*,
multinomial); TPU-native redesign: closed-form jnp math dispatched
through `core.tensor.apply`, so every method is a taped op — log_prob /
entropy / kl_divergence backprop into Tensor-valued parameters (the
policy-gradient use), and `sample(shape, seed)` derives its key from the
global generator (seed=0) or a caller seed, reproducible under
`paddle.seed` and usable inside jitted code via `core.random.key_scope`.

Semantics pinned to the reference:
- batch shape broadcasting: params broadcast together; `sample(shape)`
  returns `shape + batch_shape`, collapsed to `shape` when every param
  was a bare python float (reference :269,:491 all_arg_is_float).
- Uniform.log_prob is -inf outside [low, high) (reference :315 masks with
  lb/ub booleans and takes log of the 0/1 mask).
- Categorical takes unnormalised logits; probs/entropy/kl normalise via
  softmax over the last axis (reference :827,:862); log_prob indexes
  log_softmax directly (no exp/log underflow round-trip).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..core.tensor import Tensor, apply

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _as_param(x):
    """Param coercion (reference _to_tensor): float/list/np stay f32;
    Tensors pass through UNWRAPPED-never — the tape must keep linking
    them (e.g. Categorical(policy(states)) backprops into the policy)."""
    if isinstance(x, Tensor):
        return x
    a = jnp.asarray(x)
    if a.dtype not in (jnp.float32, jnp.float64):
        a = a.astype(jnp.float32)
    return Tensor(a)


def _as_value(v, dtype=None):
    t = v if isinstance(v, Tensor) else Tensor(jnp.asarray(v))
    if dtype is not None and str(t._data.dtype) != str(dtype):
        t = Tensor(t._data.astype(dtype))
    return t


def _sample_key(seed):
    if seed:
        return jax.random.key(int(seed))
    return random_mod.next_key()


class Distribution:
    """Abstract base (reference distribution.py:41)."""

    def sample(self, *args, **kwargs):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError


class Uniform(Distribution):
    """U(low, high) on the half-open interval (reference :168)."""

    def __init__(self, low, high, name=None):
        self.name = name or "Uniform"
        self.all_arg_is_float = isinstance(low, (int, float)) and \
            isinstance(high, (int, float))
        self.low = _as_param(low)
        self.high = _as_param(high)
        self.dtype = str(self.low._data.dtype)

    @property
    def batch_shape(self):
        return tuple(jnp.broadcast_shapes(tuple(self.low._data.shape),
                                          tuple(self.high._data.shape)))

    def sample(self, shape, seed=0):
        key = _sample_key(seed)
        out_shape = tuple(shape) + self.batch_shape
        collapse = self.all_arg_is_float

        def f(lo, hi):
            u = jax.random.uniform(key, out_shape, lo.dtype)
            out = lo + u * (hi - lo)
            return out.reshape(tuple(shape)) if collapse else out

        return apply(f, self.low, self.high, op_name="uniform_sample")

    def log_prob(self, value):
        v = _as_value(value, self.low._data.dtype)

        def f(lo, hi, vv):
            inside = jnp.logical_and(lo < vv, vv < hi)
            # log(mask) -> -inf outside the support, matching the
            # reference's log(lb*ub) construction
            return jnp.log(inside.astype(lo.dtype)) - jnp.log(hi - lo)

        return apply(f, self.low, self.high, v, op_name="uniform_log_prob")

    def probs(self, value):
        v = _as_value(value, self.low._data.dtype)

        def f(lo, hi, vv):
            inside = jnp.logical_and(lo < vv, vv < hi)
            return inside.astype(lo.dtype) / (hi - lo)

        return apply(f, self.low, self.high, v, op_name="uniform_probs")

    def entropy(self):
        return apply(lambda lo, hi: jnp.log(hi - lo), self.low, self.high,
                     op_name="uniform_entropy")


class Normal(Distribution):
    """N(loc, scale^2) (reference :390)."""

    def __init__(self, loc, scale, name=None):
        self.name = name or "Normal"
        self.all_arg_is_float = isinstance(loc, (int, float)) and \
            isinstance(scale, (int, float))
        self.loc = _as_param(loc)
        self.scale = _as_param(scale)
        self.dtype = str(self.loc._data.dtype)

    @property
    def batch_shape(self):
        return tuple(jnp.broadcast_shapes(tuple(self.loc._data.shape),
                                          tuple(self.scale._data.shape)))

    def sample(self, shape, seed=0):
        key = _sample_key(seed)
        out_shape = tuple(shape) + self.batch_shape
        collapse = self.all_arg_is_float

        def f(loc, scale):
            z = jax.random.normal(key, out_shape, loc.dtype)
            out = loc + z * scale    # reparameterised: grads flow to params
            return out.reshape(tuple(shape)) if collapse else out

        return apply(f, self.loc, self.scale, op_name="normal_sample")

    def entropy(self):
        # 0.5 + 0.5 log(2 pi) + log(scale), elementwise over batch
        return apply(
            lambda loc, s: jnp.broadcast_to(
                0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
                jnp.broadcast_shapes(loc.shape, s.shape)),
            self.loc, self.scale, op_name="normal_entropy")

    def log_prob(self, value):
        v = _as_value(value, self.loc._data.dtype)
        return apply(
            lambda loc, s, vv: -((vv - loc) ** 2) / (2.0 * s * s)
            - jnp.log(s) - 0.5 * math.log(2 * math.pi),
            self.loc, self.scale, v, op_name="normal_log_prob")

    def probs(self, value):
        v = _as_value(value, self.loc._data.dtype)
        return apply(
            lambda loc, s, vv: jnp.exp(-((vv - loc) ** 2) / (2.0 * s * s))
            / (s * math.sqrt(2 * math.pi)),
            self.loc, self.scale, v, op_name="normal_probs")

    def kl_divergence(self, other):
        """KL(self || other) for two Normals (reference :595)."""
        if not isinstance(other, Normal):
            raise TypeError("kl_divergence expects another Normal")

        def f(l0, s0, l1, s1):
            var_ratio = (s0 / s1) ** 2
            t1 = ((l0 - l1) / s1) ** 2
            return 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))

        return apply(f, self.loc, self.scale, other.loc, other.scale,
                     op_name="normal_kl")


class Categorical(Distribution):
    """Categorical over unnormalised logits (reference :640)."""

    def __init__(self, logits, name=None):
        self.name = name or "Categorical"
        self.logits = _as_param(logits)
        self.dtype = str(self.logits._data.dtype)

    def sample(self, shape, seed=0):
        """Draws category indices; output shape = shape + batch_shape
        (logits shape minus the category axis), reference :726."""
        key = _sample_key(seed)
        batch = tuple(self.logits._data.shape[:-1])
        out_shape = tuple(shape) + batch
        n = int(np.prod(shape)) if len(tuple(shape)) else 1

        def f(lg):
            draws = jax.random.categorical(key, lg, axis=-1,
                                           shape=(n,) + batch)
            return draws.reshape(out_shape).astype(jnp.int64)

        return apply(f, self.logits, op_name="categorical_sample")

    def entropy(self):
        def f(lg):
            lp = jax.nn.log_softmax(lg, axis=-1)
            return -(jnp.exp(lp) * lp).sum(-1)

        return apply(f, self.logits, op_name="categorical_entropy")

    def kl_divergence(self, other):
        if not isinstance(other, Categorical):
            raise TypeError("kl_divergence expects another Categorical")

        def f(lg_p, lg_q):
            lp = jax.nn.log_softmax(lg_p, axis=-1)
            lq = jax.nn.log_softmax(lg_q, axis=-1)
            return (jnp.exp(lp) * (lp - lq)).sum(-1)

        return apply(f, self.logits, other.logits, op_name="categorical_kl")

    @staticmethod
    def _gather(table, v):
        """Index per-category rows with broadcasting: v may carry extra
        sample dims ([S..., batch...]) or broadcast up to the batch
        shape; 1-D tables index freely with any value shape."""
        if table.ndim == 1:
            return table[v]
        batch = tuple(table.shape[:-1])
        out_shape = jnp.broadcast_shapes(tuple(v.shape), batch)
        v = jnp.broadcast_to(v, out_shape)
        t = jnp.broadcast_to(table, out_shape + table.shape[-1:])
        return jnp.take_along_axis(t, v[..., None], axis=-1)[..., 0]

    def probs(self, value):
        """Probability of the given category indices (reference :862)."""
        v = _as_value(value, jnp.int32)
        return apply(
            lambda lg, vv: self._gather(jax.nn.softmax(lg, axis=-1), vv),
            self.logits, v, op_name="categorical_probs")

    def log_prob(self, value):
        v = _as_value(value, jnp.int32)
        return apply(
            lambda lg, vv: self._gather(
                jax.nn.log_softmax(lg, axis=-1), vv),
            self.logits, v, op_name="categorical_log_prob")
