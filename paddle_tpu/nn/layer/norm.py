"""Norm layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm",
           "SpectralNorm", "RMSNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        if weight_attr is False:
            self.weight = None
        if bias_attr is False:
            self.bias = None
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features],
                                                       jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features],
                                                          jnp.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """1.x-style alias (fluid.dygraph.BatchNorm)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCW" if data_format == "NCL" else "NLC",
                         use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. Under pjit/shard_map the batch axis is
    sharded and XLA computes global-batch statistics automatically when the
    reduction spans the mesh axis (reference: sync_batch_norm_op.cu needed an
    explicit NCCL allreduce; here the mean/var reductions are global by
    construction of the sharded program)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            out = cls(layer._num_features, layer._momentum, layer._epsilon,
                      None, None, layer._data_format)
            if layer.weight is not None:
                out.weight.set_value(layer.weight)
            if layer.bias is not None:
                out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(self._normalized_shape,
                                          attr=bias_attr, is_bias=True)
        if weight_attr is False:
            self.weight = None
        if bias_attr is False:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """Root-mean-square norm (no reference analog — standard for modern LLM
    blocks; used by the GPT model zoo)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        import jax

        from ...core.tensor import apply
        eps = self._epsilon

        def f(a, w):
            x32 = a.astype(jnp.float32)
            ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
            return (x32 * jax.lax.rsqrt(ms + eps) * w.astype(jnp.float32)) \
                .astype(a.dtype)
        return apply(f, x, self.weight, op_name="rms_norm")


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                          is_bias=True)
        if weight_attr is False:
            self.weight = None
        if bias_attr is False:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon,
                               data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Power-iteration spectral norm (reference: spectral_norm_op)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0.0, 1.0))
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        import jax

        from ...core.tensor import apply
        dim, iters, eps = self._dim, self._power_iters, self._eps

        def f(w, u, v):
            mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return w / sigma
        return apply(f, weight, self.weight_u, self.weight_v,
                     op_name="spectral_norm")
