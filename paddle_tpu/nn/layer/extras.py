"""Remaining reference nn classes: PairwiseDistance, HSigmoidLoss,
NCELoss, TreeConv (reference: python/paddle/nn/layer/distance.py:26,
nn/functional/loss.py hsigmoid_loss wrapper classes,
fluid/dygraph/nn.py:3096 TreeConv + operators/math/tree2col.cc)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, apply
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["PairwiseDistance", "HSigmoidLoss", "NCELoss", "TreeConv",
           "ctc_greedy_decoder"]


class PairwiseDistance(Layer):
    """p-norm of x - y over axis 1 (reference nn/layer/distance.py:26)."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = float(p)
        self.epsilon = float(epsilon)
        self.keepdim = bool(keepdim)

    def forward(self, x, y):
        p, eps, keep = self.p, self.epsilon, self.keepdim

        def f(a, b):
            d = jnp.abs(a - b) + eps
            if p == float("inf"):
                out = jnp.max(d, axis=1, keepdims=keep)
            else:
                out = jnp.sum(d ** p, axis=1, keepdims=keep) ** (1.0 / p)
            return out
        return apply(f, x, y, op_name="pairwise_distance")


class HSigmoidLoss(Layer):
    """Hierarchical-sigmoid classifier head (reference paddle.nn
    HSigmoidLoss over nn/functional/loss.py:331). Owns the
    [num_classes - 1, feature_size] weight and optional bias."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if num_classes < 2:
            raise ValueError("num_classes must be >= 2")
        self._num_classes = num_classes
        self._is_custom = is_custom
        rows = num_classes if is_custom else num_classes - 1
        self.weight = self.create_parameter(
            [rows, feature_size], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter([rows, 1], attr=bias_attr,
                                          is_bias=True)
        if bias_attr is False:
            self.bias = None

    def forward(self, input, label, path_table=None, path_code=None):
        if self._is_custom and (path_table is None or path_code is None):
            raise ValueError("custom tree needs path_table and path_code")
        return F.hsigmoid_loss(input, label, self._num_classes, self.weight,
                               self.bias, path_table=path_table,
                               path_code=path_code)


class NCELoss(Layer):
    """Noise-contrastive estimation head (reference nn __all__ NCELoss /
    fluid nce): owns [num_total_classes, dim] weight + bias and samples
    negatives per call."""

    def __init__(self, num_total_classes, dim, num_neg_samples=10,
                 sampler="uniform", custom_dist=None, seed=0,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self._num_total_classes = num_total_classes
        self._kw = dict(num_neg_samples=num_neg_samples, sampler=sampler,
                        custom_dist=custom_dist, seed=seed)
        self.weight = self.create_parameter(
            [num_total_classes, dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter([num_total_classes], attr=bias_attr,
                                          is_bias=True)
        if bias_attr is False:
            self.bias = None

    def forward(self, input, label, sample_weight=None):
        return F.nce(input, label, self._num_total_classes, self.weight,
                     self.bias, sample_weight=sample_weight, **self._kw)


def _tree_patches(edges, n_nodes, max_depth):
    """Continuous-binary-tree patch coefficients
    (tree2col.cc construct_patch): DFS from each node bounded by
    max_depth; eta_t = (d_max - depth)/d_max,
    eta_l = (1 - eta_t) * (index-1)/(pclen-1) (0.5 single child),
    eta_r = (1 - eta_t)(1 - eta_l). Returns [P, n_nodes, 3] coeffs."""
    tr = [[] for _ in range(n_nodes + 2)]
    for u, v in edges:
        if u == 0 and v == 0:
            break
        if u != 0 and v != 0:
            tr[int(u)].append(int(v))
    coeffs = []
    for root in range(1, n_nodes + 1):
        # (node, index, pclen, depth)
        patch = [(root, 1, 1, 0)]
        stack = [(root, 1, 1, 0)]
        visited = {root}
        while stack:
            node, _, _, depth = stack[-1]
            pushed = False
            kids = tr[node]
            for i, v in enumerate(kids):
                if v not in visited and depth + 1 < max_depth:
                    visited.add(v)
                    stack.append((v, i, len(kids), depth + 1))
                    patch.append((v, i + 1, len(kids), depth + 1))
                    pushed = True
                    break
            if not pushed:
                stack.pop()
        c = np.zeros((n_nodes, 3))
        for node, index, pclen, depth in patch:
            eta_t = (max_depth - depth) / max_depth
            tmp = 0.5 if pclen == 1 else (index - 1.0) / (pclen - 1.0)
            eta_l = (1.0 - eta_t) * tmp
            eta_r = (1.0 - eta_t) * (1.0 - eta_l)
            c[node - 1, 0] += eta_l
            c[node - 1, 1] += eta_r
            c[node - 1, 2] += eta_t
        coeffs.append(c)
    return np.stack(coeffs)         # [P, n_nodes, 3]


class TreeConv(Layer):
    """Tree-based convolution (fluid/dygraph/nn.py:3096; kernel
    tree_conv_op.h + tree2col.cc). nodes_vector [B, n, feature_size],
    edge_set [B, n_edges, 2] int (1-based parent/child, 0-padded).
    Output [B, n, output_size, num_filters] (act applied).

    The patch coefficients depend only on the integer tree structure, so
    they're built host-side; the feature contraction stays jnp and
    differentiable through nodes_vector and the filter."""

    def __init__(self, feature_size, output_size, num_filters=1, max_depth=2,
                 act="tanh", param_attr=None, bias_attr=None, name=None):
        super().__init__()
        self._feature_size = feature_size
        self._output_size = output_size
        self._num_filters = num_filters
        self._max_depth = max_depth
        self._act = act
        self.weight = self.create_parameter(
            [feature_size, 3, output_size, num_filters], attr=param_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter([1, output_size, num_filters],
                                          attr=bias_attr, is_bias=True)
        if bias_attr is False:
            self.bias = None

    def forward(self, nodes_vector, edge_set):
        edges = np.asarray(edge_set.numpy()
                           if isinstance(edge_set, Tensor) else edge_set)
        feats_shape = nodes_vector.shape
        b, n = int(feats_shape[0]), int(feats_shape[1])
        coeff = np.stack([_tree_patches(edges[i], n, self._max_depth)
                          for i in range(b)])       # [B, P, n, 3]
        coeff_j = jnp.asarray(coeff, jnp.float32)
        act = self._act

        def f(x, w, *maybe_b):
            # patch[b, p, i, k] = sum_v coeff[b, p, v, k] * x[b, v, i]
            patch = jnp.einsum("bpvk,bvi->bpik", coeff_j, x)
            out = jnp.einsum("bpik,ikof->bpof", patch, w)
            if maybe_b:
                out = out + maybe_b[0][None]
            if act == "tanh":
                out = jnp.tanh(out)
            elif act == "relu":
                out = jnp.maximum(out, 0)
            elif act is not None:
                raise ValueError("TreeConv act supports tanh/relu/None")
            return out
        args = [nodes_vector, self.weight] + (
            [self.bias] if self.bias is not None else [])
        return apply(f, *args, op_name="tree_conv")


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    """Greedy CTC decode (fluid/layers/nn.py:5271): per-step argmax,
    merge repeats, drop blanks. Padded mode: input [B, T, C] probs,
    returns (decoded [B, T] padded with padding_value, lengths [B, 1])."""
    x = np.asarray(input.numpy() if isinstance(input, Tensor) else input)
    if x.ndim != 3:
        raise ValueError("ctc_greedy_decoder expects padded [B, T, C] input "
                         "(LoD mode is expressed via input_length)")
    b, t, _ = x.shape
    lens = (np.full(b, t, np.int64) if input_length is None
            else np.asarray(input_length.numpy()
                            if isinstance(input_length, Tensor)
                            else input_length).reshape(-1).astype(np.int64))
    am = x.argmax(axis=2)
    out = np.full((b, t), padding_value, np.int64)
    out_lens = np.zeros((b, 1), np.int64)
    for i in range(b):
        prev = -1
        k = 0
        for j in range(int(lens[i])):
            tok = int(am[i, j])
            if tok != prev and tok != blank:
                out[i, k] = tok
                k += 1
            prev = tok
        out_lens[i, 0] = k
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(out_lens))
