"""StaticRNN / DynamicRNN — the fluid with-block RNN builders, eager.

Reference: fluid/layers/control_flow.py StaticRNN:448 (step/step_input/
memory/update_memory/step_output protocol, time on dim 0) and DynamicRNN
(fluid/layers/control_flow.py:2878 — block/step_input/memory/
update_memory/output over LoD sequences).

The reference executes the with-block ONCE to build a Program block that
the executor replays per timestep. Eager equivalent: the with-block's
source is recovered from the calling frame (the same AST machinery as
jit/ast_transform), compiled into a step function, and re-executed per
timestep with the builder in replay mode — step_input yields step t's
slice, memory carries state, update_memory/step_output record. The
initial with-block pass runs on step-0 data purely to type-check user
code (its results are discarded), matching the reference's build pass.

DynamicRNN rides the padded-dense sequence form (core/lod.py): inputs
[B, T, ...] with `lengths`; finished sequences hold their memory and pad
their outputs with zeros.
"""
from __future__ import annotations

import ast
import inspect
import textwrap

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, apply

__all__ = ["StaticRNN", "DynamicRNN"]


class _StepCtx:
    def __init__(self, rnn):
        self._rnn = rnn

    def __enter__(self):
        frame = inspect.stack()[1].frame
        self._rnn._capture_frame(frame)
        self._rnn._mode = "build"
        return self

    def __exit__(self, exc_type, exc, tb):
        self._rnn._mode = "after"
        return False


def _find_with_body(func_source, lineno_rel, ctx_name):
    """The statement list of the `with <...>.step()/block():` at (or
    nearest above) the given source line."""
    tree = ast.parse(func_source)
    best = None
    for node in ast.walk(tree):
        if isinstance(node, ast.With) and node.lineno <= lineno_rel:
            if best is None or node.lineno > best.lineno:
                src = ast.get_source_segment(func_source, node.items[0]
                                             .context_expr) or ""
                if ctx_name in src:
                    best = node
    if best is None:
        raise RuntimeError(
            f"could not locate the `with ...{ctx_name}()` block in the "
            "calling function's source (the builders need readable "
            "source, like the reference's program capture)")
    return best.body


class _RnnBuilderBase:
    """Shared engine: capture the with-block, replay per timestep."""

    _CTX_NAME = "step"

    def __init__(self, name=None):
        self._mode = "before"
        self._inputs = []          # raw [T, B, ...] (time-major)
        self._lengths = None
        self._mems = []            # dicts: init, current, update
        self._outputs = []         # marker ids registered via step_output
        self._t = 0
        self._n_steps = None
        self._frame_info = None
        self._step_code = None
        self._seen_inputs = 0
        self._seen_mems = 0

    # -- capture -----------------------------------------------------------
    def _capture_frame(self, frame):
        self._frame_info = {
            "locals": dict(frame.f_locals),
            "globals": frame.f_globals,
            "lineno": frame.f_lineno,
            "code": frame.f_code,
        }

    def _compile_step(self):
        info = self._frame_info
        try:
            if info["code"].co_name == "<module>":
                # getsource on module code yields only the first logical
                # line; take the whole file instead
                import linecache
                lines = linecache.getlines(info["code"].co_filename)
                if not lines:
                    raise OSError("no source lines")
                src = "".join(lines)
                first = 1
            else:
                src = textwrap.dedent(inspect.getsource(info["code"]))
                first = info["code"].co_firstlineno
            rel = info["lineno"] - first + 1
        except (OSError, TypeError) as e:
            raise RuntimeError(
                f"{type(self).__name__}: cannot read the caller's source "
                f"({e}); the with-block builders need it") from None
        body = _find_with_body(src, rel, self._CTX_NAME)
        mod = ast.Module(body=list(body), type_ignores=[])
        ast.increment_lineno(mod, 0)
        ast.fix_missing_locations(mod)
        self._step_code = compile(
            mod, filename=f"<{type(self).__name__} step>", mode="exec")

    # -- user protocol -----------------------------------------------------
    def memory(self, init=None, shape=None, batch_ref=None, value=0.0,
               init_value=0.0, dtype="float32", **kw):
        if self._mode == "build":
            if init is not None:
                t = init if isinstance(init, Tensor) else \
                    Tensor(jnp.asarray(init))
            else:
                if batch_ref is not None:
                    b = (batch_ref.shape[0] if not isinstance(
                        batch_ref, Tensor) else int(batch_ref.shape[0]))
                else:
                    b = self._batch_size()
                dims = [b if (d is None or int(d) < 0) else int(d)
                        for d in (shape or [])]
                t = Tensor(jnp.full(tuple(dims),
                                    float(value or init_value),
                                    jnp.dtype(dtype)))
            # memories stay TENSORS across steps so the tape chains the
            # whole unrolled recurrence (BPTT through the builder)
            self._mems.append({"init": t, "cur": t, "new": None})
            return t
        m = self._mems[self._seen_mems]
        self._seen_mems += 1
        return m["cur"]

    def update_memory(self, mem, var):
        # slot selected by the IDENTITY of `mem` (multi-memory blocks —
        # e.g. LSTM h and c — must each update their own slot)
        i = None
        for j, m in enumerate(self._mems):
            if m["cur"] is mem or m["init"] is mem:
                i = j
                break
        if i is None:
            i = (self._seen_mems - 1 if self._mode == "replay"
                 else len(self._mems) - 1)
        new = var if isinstance(var, Tensor) else Tensor(jnp.asarray(var))
        if self._mode == "replay" and self._lengths is not None:
            cur = self._mems[i]["cur"]
            t_now = self._t
            lengths = self._lengths

            def f(n_, c_):
                active = (t_now < lengths)
                shp = (-1,) + (1,) * (n_.ndim - 1)
                return jnp.where(active.reshape(shp), n_, c_)
            new = apply(f, new, cur, op_name="drnn_mask")
        self._mems[i]["new"] = new

    def __call__(self):
        if self._mode != "after":
            raise RuntimeError("call the RNN after the with-block closes")
        return self._run()

    # -- engine ------------------------------------------------------------
    _BATCH_DIM = 1          # StaticRNN: [T, B, ...]

    def _batch_size(self):
        if not self._inputs:
            raise ValueError("memory(shape with -1) needs a step_input "
                             "first (or pass batch_ref)")
        return int(self._inputs[0].shape[self._BATCH_DIM])

    def _run(self):
        self._compile_step()
        self._mode = "replay"
        for m in self._mems:
            m["cur"] = m["init"]
        outs = []
        info = self._frame_info
        for t in range(self._n_steps):
            self._t = t
            self._seen_inputs = 0
            self._seen_mems = 0
            self._step_outs = []
            # ONE merged namespace as globals AND locals: with separate
            # dicts, lambdas/genexprs in the block could not see names
            # the block itself assigns (exec writes them to locals only)
            ns = dict(info["globals"])
            ns.update(info["locals"])
            exec(self._step_code, ns)
            for m in self._mems:
                if m["new"] is not None:
                    m["cur"] = m["new"]
                    m["new"] = None
            outs.append(list(self._step_outs))
        self._mode = "after"
        return self._assemble(outs)


class StaticRNN(_RnnBuilderBase):
    """fluid.layers.StaticRNN (control_flow.py:448): inputs are
    time-major [T, B, ...]; rnn() returns the stacked step outputs
    [T, B, ...] (a tuple when multiple step_outputs)."""

    _CTX_NAME = "step"

    def step(self):
        return _StepCtx(self)

    def step_input(self, x):
        t_in = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
        if self._mode == "build":
            # the TENSOR is kept so replay slices through the tape —
            # grads reach whatever produced the input (embeddings etc.)
            self._inputs.append(t_in)
            n = t_in.shape[0]
            if self._n_steps is None:
                self._n_steps = int(n)
            elif self._n_steps != int(n):
                raise ValueError("step_input sequence lengths disagree")
            return apply(lambda a: a[0], t_in, op_name="rnn_step_in")
        i = self._seen_inputs
        self._seen_inputs += 1
        t_now = self._t
        return apply(lambda a: a[t_now], self._inputs[i],
                     op_name="rnn_step_in")

    def step_output(self, o):
        if self._mode == "build":
            self._outputs.append(None)
            return
        self._step_outs.append(o if isinstance(o, Tensor)
                               else Tensor(jnp.asarray(o)))

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _assemble(self, outs):
        res = []
        for k in range(len(outs[0])):
            steps = [outs[t][k] for t in range(self._n_steps)]
            res.append(apply(lambda *xs: jnp.stack(xs), *steps,
                             op_name="static_rnn_stack"))
        return res[0] if len(res) == 1 else tuple(res)


class DynamicRNN(_RnnBuilderBase):
    """fluid DynamicRNN (control_flow.py:2878) on the padded-dense form:
    step_input takes (x [B, T, ...], lengths); finished sequences freeze
    their memory and pad outputs with zeros. drnn() returns the padded
    [B, T, ...] outputs (tuple when multiple)."""

    _CTX_NAME = "block"
    _BATCH_DIM = 0          # DynamicRNN: [B, T, ...]

    def block(self):
        return _StepCtx(self)

    def step_input(self, x, lengths=None, level=0):
        t_in = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
        if self._mode == "build":
            self._inputs.append(t_in)           # batch-major [B, T, ...]
            n = int(t_in.shape[1])
            if self._n_steps is None:
                self._n_steps = n
            elif self._n_steps != n:
                # jnp index clamping would silently repeat the shorter
                # input's last step — refuse instead (StaticRNN does too)
                raise ValueError(
                    f"step_input sequence lengths disagree "
                    f"({self._n_steps} vs {n}); pad inputs to one T and "
                    "pass lengths")
            if lengths is not None:
                ln = lengths._data if isinstance(lengths, Tensor) else \
                    jnp.asarray(lengths)
                self._lengths = ln.reshape(-1)
            return apply(lambda a: a[:, 0], t_in, op_name="drnn_step_in")
        i = self._seen_inputs
        self._seen_inputs += 1
        t_now = self._t
        return apply(lambda a: a[:, t_now], self._inputs[i],
                     op_name="drnn_step_in")

    def output(self, *outputs):
        if self._mode == "build":
            for _ in outputs:
                self._outputs.append(None)
            return
        for o in outputs:
            self._step_outs.append(o if isinstance(o, Tensor)
                                   else Tensor(jnp.asarray(o)))

    def _assemble(self, outs):
        n_steps = self._n_steps
        lengths = self._lengths
        res = []
        for k in range(len(outs[0])):
            steps = [outs[t][k] for t in range(n_steps)]

            def f(*xs):
                s = jnp.swapaxes(jnp.stack(xs), 0, 1)   # [B, T, ...]
                if lengths is not None:
                    tpos = jnp.arange(n_steps)
                    mask = tpos[None, :] < lengths[:, None]
                    shape = mask.shape + (1,) * (s.ndim - 2)
                    s = jnp.where(mask.reshape(shape), s, 0)
                return s
            res.append(apply(f, *steps, op_name="dynamic_rnn_stack"))
        return res[0] if len(res) == 1 else tuple(res)
