"""Seq2seq decoding: Decoder, BeamSearchDecoder, dynamic_decode,
gather_tree.

Reference: fluid/layers/rnn.py — Decoder:790, BeamSearchDecoder:866,
dynamic_decode:1581; gather_tree op (fluid/layers/nn.py gather_tree,
kernel gather_tree_op.h).

TPU-native design: the decode loop is a lax.while_loop over preallocated
[max_step, batch, beam] buffers — static shapes, so the same code runs
eagerly AND exports/jits (the reference builds a dynamic While program
with growing LoDTensorArrays, which XLA cannot express). Finished beams
are masked to emit only EOS exactly like the reference's noend mask
(_beam_search_step, kinf = 1e9).
"""
from __future__ import annotations

from collections import namedtuple

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode", "gather_tree"]

_KINF = 1e9


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _map(fn, struct):
    """map over a (possibly nested tuple/list) structure of tensors."""
    if isinstance(struct, (tuple, list)):
        return type(struct)(_map(fn, s) for s in struct)
    return fn(struct)


def _unwrap_tree(t):
    """Tensor leaves -> raw arrays; namedtuples/tuples/dicts stay pytrees."""
    return jax.tree_util.tree_map(
        lambda x: x._data if isinstance(x, Tensor) else jnp.asarray(x), t,
        is_leaf=lambda x: isinstance(x, Tensor))


class Decoder:
    """Decoder interface (fluid/layers/rnn.py:790): initialize / step /
    finalize contract used by dynamic_decode."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam-search wrapper over an RNN cell
    (fluid/layers/rnn.py:866). Cell inputs/states ride merged
    [batch*beam, ...] layout; scores accumulate log-softmax
    probabilities; finished beams emit only end_token."""

    OutputWrapper = namedtuple("OutputWrapper",
                               ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = namedtuple("StateWrapper",
                              ("cell_states", "log_probs", "finished",
                               "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] with each row repeated beam times
        (reference rnn.py tile_beam_merge_with_batch)."""
        a = _arr(x)
        out = jnp.repeat(a, beam_size, axis=0)
        return Tensor(out) if isinstance(x, Tensor) else out

    # -- layout helpers ----------------------------------------------------
    def _merge(self, a):
        return a.reshape((-1,) + a.shape[2:])           # [B, K, ...] -> BK

    def _split(self, a):
        return a.reshape((-1, self.beam_size) + a.shape[1:])

    def _gather_beams(self, a, beam_indices):
        """a [B, K, ...]; beam_indices [B, K] -> rows reordered per beam."""
        b = a.shape[0]
        return a[jnp.arange(b)[:, None], beam_indices]

    # -- Decoder interface (raw-array core) --------------------------------
    def initialize(self, inits):
        """inits: cell states [B, ...] (nested). Returns (inputs, state,
        finished) with state a StateWrapper; log probs start [0, -inf...]
        so step 0 expands only beam 0 (reference rnn.py:281-283)."""
        cell_states = _map(lambda s: jnp.repeat(_arr(s), self.beam_size,
                                                axis=0), inits)
        first = jax.tree_util.tree_leaves(_unwrap_tree(cell_states))[0]
        b = first.shape[0] // self.beam_size
        log_probs = jnp.tile(
            jnp.asarray([[0.0] + [-_KINF] * (self.beam_size - 1)],
                        jnp.float32), (b, 1))
        finished = jnp.zeros((b, self.beam_size), bool)
        lengths = jnp.zeros((b, self.beam_size), jnp.int32)
        ids = jnp.full((b, self.beam_size), self.start_token, jnp.int32)
        inputs = self._embed(ids)
        return inputs, self.StateWrapper(cell_states, log_probs, finished,
                                         lengths), finished

    def _embed(self, ids):
        if self.embedding_fn is None:
            return self._merge(ids)
        out = self.embedding_fn(Tensor(self._merge(ids)))
        return _arr(out)

    def step(self, time, inputs, states, **kwargs):
        cell_out, next_cell_states = self.cell(
            Tensor(inputs), _map(Tensor, states.cell_states))
        logits = cell_out
        if self.output_fn is not None:
            logits = self.output_fn(logits)
        logits = self._split(_arr(logits))              # [B, K, V]
        next_cell_states = _map(_arr, next_cell_states)
        vocab = logits.shape[-1]

        step_lp = jax.nn.log_softmax(logits)
        # finished beams: all mass on end_token (noend mask)
        noend = jnp.full((vocab,), -_KINF).at[self.end_token].set(0.0)
        step_lp = jnp.where(states.finished[:, :, None], noend[None, None],
                            step_lp)
        log_probs = step_lp + states.log_probs[:, :, None]   # [B, K, V]
        b = log_probs.shape[0]
        scores = log_probs.reshape(b, self.beam_size * vocab)
        topk_scores, topk_idx = jax.lax.top_k(scores, self.beam_size)
        beam_idx = topk_idx // vocab                     # [B, K]
        token_idx = (topk_idx % vocab).astype(jnp.int32)

        next_cell_states = _map(
            lambda a: self._merge(self._gather_beams(self._split(a),
                                                     beam_idx)),
            next_cell_states)
        next_finished = self._gather_beams(states.finished, beam_idx)
        next_lengths = self._gather_beams(states.lengths, beam_idx)
        next_lengths = next_lengths + (~next_finished).astype(jnp.int32)
        next_finished = next_finished | (token_idx == self.end_token)

        outputs = self.OutputWrapper(topk_scores, token_idx,
                                     beam_idx.astype(jnp.int32))
        next_states = self.StateWrapper(next_cell_states, topk_scores,
                                        next_finished, next_lengths)
        next_inputs = self._embed(token_idx)
        return outputs, next_states, next_inputs, next_finished

    def finalize(self, outputs, final_states, sequence_lengths):
        """Trace parent pointers back into whole sequences
        (reference finalize -> gather_tree)."""
        predicted = _gather_tree_arrays(outputs.predicted_ids,
                                        outputs.parent_ids)
        return predicted, final_states

    @property
    def tracks_own_finished(self):
        return True


def _gather_tree_arrays(ids, parents):
    """ids/parents [T, B, K] -> full beams [T, B, K]
    (kernel gather_tree_op.h backward trace)."""
    t = ids.shape[0]

    def body(carry, xs):
        beam = carry                     # [B, K] current beam pointer
        ids_t, parents_t = xs
        b = ids_t.shape[0]
        tok = ids_t[jnp.arange(b)[:, None], beam]
        nxt = parents_t[jnp.arange(b)[:, None], beam]
        return nxt, tok
    k = ids.shape[-1]
    init = jnp.broadcast_to(jnp.arange(k), ids.shape[1:]).astype(
        parents.dtype)
    _, toks = jax.lax.scan(body, init, (ids[::-1], parents[::-1]))
    return toks[::-1]


def gather_tree(ids, parents):
    """Public gather_tree (fluid/layers/nn.py gather_tree): [T, B, K]
    int tensors."""
    out = _gather_tree_arrays(_arr(ids), _arr(parents))
    return Tensor(out)


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run the decoder until every beam finished or max_step_num
    (fluid/layers/rnn.py:1581). Returns (outputs, final_states[,
    sequence_lengths]). Works with BeamSearchDecoder and any custom
    Decoder following the initialize/step/finalize contract (states and
    outputs may be arbitrary pytrees, namedtuples included).

    TPU note: outputs live in [max_step_num, ...] buffers inside a
    lax.while_loop, so the decode jits and exports; max_step_num=None
    falls back to 256 steps (the reference's unbounded While cannot have
    static shapes). Buffer rows past the stop step hold the step-0
    template values — for BeamSearchDecoder the parent buffer pads with
    the identity permutation so gather_tree passes through them
    untouched."""
    max_t = int(max_step_num) if max_step_num is not None else 256

    inputs0, states0, finished0 = decoder.initialize(inits)
    inputs0 = _unwrap_tree(inputs0)
    states0 = _unwrap_tree(states0)
    finished0 = _arr(finished0)

    # run step 0 outside the loop: its outputs define the buffer shapes
    out0, st1, in1, fin1 = decoder.step(jnp.asarray(0), inputs0, states0,
                                        **kwargs)
    out0 = _unwrap_tree(out0)
    st1 = _unwrap_tree(st1)
    in1 = _unwrap_tree(in1)
    fin1 = _arr(fin1)
    own_fin = bool(getattr(decoder, "tracks_own_finished", False))
    fin_acc1 = fin1 if own_fin else (finished0 | fin1)
    lengths1 = (~finished0).astype(jnp.int32)

    flat_out0, out_def = jax.tree_util.tree_flatten(out0)
    flat_st1, st_def = jax.tree_util.tree_flatten(st1)
    is_beam_out = isinstance(out0, BeamSearchDecoder.OutputWrapper)

    bufs = []
    for i, a in enumerate(flat_out0):
        if is_beam_out and i == 2:
            # parent_ids: identity padding so gather_tree's backward
            # trace passes through unexecuted rows unchanged
            k = a.shape[-1]
            init = jnp.broadcast_to(jnp.arange(k, dtype=a.dtype),
                                    (max_t,) + a.shape)
        else:
            init = jnp.zeros((max_t,) + a.shape, a.dtype)
        bufs.append(init.at[0].set(a))

    def cond(carry):
        t = carry[0]
        fin = carry[3]
        return jnp.logical_and(t < max_t, ~jnp.all(fin))

    def body(carry):
        t, inputs, flat_st, fin, lengths, bufs_c = carry
        states = jax.tree_util.tree_unflatten(st_def, flat_st)
        out, next_st, next_in, step_fin = decoder.step(
            t, inputs, states, **kwargs)
        out = _unwrap_tree(out)
        next_st = _unwrap_tree(next_st)
        next_in = _unwrap_tree(next_in)
        step_fin = _arr(step_fin)
        next_fin = step_fin if own_fin else (fin | step_fin)
        next_lengths = lengths + (~fin).astype(jnp.int32)
        if impute_finished:
            old_flat = flat_st
            new_flat = jax.tree_util.tree_flatten(next_st)[0]
            mask = fin.reshape(-1)
            imputed = [jnp.where(mask.reshape((-1,) + (1,) * (n.ndim - 1)),
                                 o, n) if n.shape[:1] == mask.shape else n
                       for o, n in zip(old_flat, new_flat)]
            next_st = jax.tree_util.tree_unflatten(st_def, imputed)
        flat_o = jax.tree_util.tree_flatten(out)[0]
        bufs_n = [b.at[t].set(a) for b, a in zip(bufs_c, flat_o)]
        return (t + 1, next_in,
                jax.tree_util.tree_flatten(next_st)[0],
                next_fin, next_lengths, bufs_n)

    carry0 = (jnp.asarray(1), in1, flat_st1, fin_acc1, lengths1, bufs)
    (t_end, _, flat_st, fin, lengths, bufs) = jax.lax.while_loop(
        cond, body, carry0)

    final_states_raw = jax.tree_util.tree_unflatten(st_def, flat_st)
    outputs_raw = jax.tree_util.tree_unflatten(out_def, bufs)
    # for decoders carrying lengths in their state (BeamSearchDecoder),
    # the state's count is authoritative
    seq_lengths = getattr(final_states_raw, "lengths", lengths)

    final_states = jax.tree_util.tree_map(Tensor, final_states_raw)
    try:
        finalized, _ = decoder.finalize(outputs_raw, final_states,
                                        seq_lengths)
        out_tree = _unwrap_tree(finalized)
    except NotImplementedError:
        out_tree = outputs_raw

    # trim to executed steps (concrete eagerly; padded extent under jit)
    try:
        n_valid = int(t_end)
        out_tree = jax.tree_util.tree_map(lambda a: a[:n_valid], out_tree)
    except (TypeError, jax.errors.TracerIntegerConversionError,
            jax.errors.ConcretizationTypeError):
        pass
    if not output_time_major:
        out_tree = jax.tree_util.tree_map(
            lambda a: jnp.swapaxes(a, 0, 1), out_tree)
    out_tree = jax.tree_util.tree_map(Tensor, out_tree)
    res = [out_tree, final_states]
    if return_length:
        res.append(Tensor(seq_lengths))
    return tuple(res)
