"""Transformer layers (reference: python/paddle/nn/layer/transformer.py:115
MultiHeadAttention, :437 TransformerEncoderLayer, ... :1360).

TPU-native notes: attention goes through F.scaled_dot_product_attention which
dispatches to the Pallas flash kernel; all projections are MXU matmuls; the
[batch, seq, heads, head_dim] layout is kept throughout so sequence/tensor
sharding specs apply cleanly under pjit.
"""
from __future__ import annotations

import collections
import contextlib

import numpy as np

from ...core.tensor import Tensor
from ...framework import Parameter
from ...ops import manipulation as M
from .. import functional as F
from .common import Dropout, Linear
from .container import LayerList
from .layers import Layer
from .norm import LayerNorm

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer",
           "TransformerEncoder", "TransformerDecoderLayer",
           "TransformerDecoder", "Transformer", "ScanBlockStack"]


def _convert_attention_mask(attn_mask, dtype):
    if attn_mask is None:
        return None
    if attn_mask.dtype == np.bool_ or str(attn_mask.dtype) == "bool":
        return attn_mask
    return attn_mask


class MultiHeadAttention(Layer):
    """reference: nn/layer/transformer.py:115."""

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split_heads(self, x):
        # [B, S, E] -> [B, S, H, D]
        return M.reshape(x, list(x.shape[:2]) + [self.num_heads, self.head_dim])

    def _prepare_qkv(self, query, key, value, cache=None):
        q = self._split_heads(self.q_proj(query))
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value))
        if isinstance(cache, self.Cache):
            k = M.concat([cache.k, k], axis=1)
            v = M.concat([cache.v, v], axis=1)
            cache = self.Cache(k, v)
        return q, k, v, cache

    def gen_cache(self, key, value=None, type=Cache):
        if type == MultiHeadAttention.StaticCache:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value if value is not None
                                              else key))
            return self.StaticCache(k, v)
        from ...ops.creation import zeros
        batch = key.shape[0] if isinstance(key, Tensor) else key
        k = zeros([batch, 0, self.num_heads, self.head_dim])
        v = zeros([batch, 0, self.num_heads, self.head_dim])
        return self.Cache(k, v)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q, k, v, cache = self._prepare_qkv(query, key, value, cache)
        attn_mask = _convert_attention_mask(attn_mask, q.dtype)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            training=self.training)
        out = M.reshape(out, list(out.shape[:2]) + [self.embed_dim])
        out = self.out_proj(out)
        outs = [out]
        if self.need_weights:
            outs.append(None)  # flash path does not materialize probs
        if cache is not None:
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)


class TransformerEncoderLayer(Layer):
    """reference: nn/layer/transformer.py:437."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, incremental_cache = self.self_attn(src, src, src, src_mask,
                                                    cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, incremental_cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class ScanBlockStack(Layer):
    """A stack of homogeneous blocks run as ONE ``jax.lax.scan`` step.

    The per-block parameters are stacked along a leading ``layers`` axis
    and registered on this container under the block-relative names (e.g.
    ``attn.qkv.weight`` with shape ``[L, ...]``), so the traced HLO — and
    therefore XLA compile time — is (near-)invariant in depth. The first
    block is kept (unregistered) as the structural template the scan body
    calls through ``framework.functional_call``.

    Checkpoints stay layout-independent: ``state_dict`` exports canonical
    per-block ``{i}.{rel}`` entries and ``set_state_dict`` accepts either
    layout (via the ``_expand_state_dict``/``_collapse_state_dict`` hooks
    consumed by ``Layer.state_dict``/``set_state_dict``).

    Remat composes: ``set_recompute(True, policy)`` wraps the scan body in
    ``jax.checkpoint`` so activation memory stays bounded by one block.
    ``set_unroll(True)`` is the escape hatch that runs the same stacked
    parameters through a Python loop (used for debugging and by
    ``DistributedStrategy.scan_layers = False``).
    """

    # marker the fleet compiler reads: dim 0 of every param here is a
    # lax.scan xs axis and must never take a mesh-axis split
    _scan_stack = True

    def __init__(self, blocks):
        super().__init__()
        blocks = list(blocks)
        if not blocks:
            raise ValueError("ScanBlockStack needs at least one block")
        import jax.numpy as jnp
        template = blocks[0]
        if dict(template.named_buffers()):
            raise NotImplementedError(
                "ScanBlockStack blocks must be buffer-free (stateful "
                "buffers cannot ride a scan carry); use an unrolled "
                "LayerList instead")
        self.num_layers = len(blocks)
        # keep the template OUT of _sub_layers / named_parameters: it is
        # structure only — its weights are shadowed by the stacked ones
        self.__dict__["_scan_template"] = template
        self._rels = [n for n, _ in template.named_parameters()]
        per_block = [dict(b.named_parameters()) for b in blocks]
        for rel in self._rels:
            stacked = jnp.stack([pb[rel]._data for pb in per_block])
            p = Parameter(stacked, trainable=True)
            # rel names contain dots; register directly (bypasses
            # __setattr__, which is attribute-name based anyway)
            self._parameters[rel] = p
        self._recompute = False
        self._recompute_policy = None
        self._unroll = False

    # -- template access (pipeline_fns etc. read config attrs off blk[0]) --
    @property
    def template(self):
        return self.__dict__["_scan_template"]

    def __len__(self):
        return self.num_layers

    def __getitem__(self, idx):
        # every block is structurally identical; hand out the template
        # for config reads (ln eps, capacity factors, ...)
        if not -self.num_layers <= idx < self.num_layers:
            raise IndexError(idx)
        return self.template

    # -- knobs --------------------------------------------------------------
    def set_recompute(self, flag, policy=None):
        self._recompute = bool(flag)
        self._recompute_policy = policy

    def set_unroll(self, flag):
        self._unroll = bool(flag)

    # -- forward ------------------------------------------------------------
    def forward(self, x, *extras):
        import jax

        from ...core import random as random_mod
        from ...framework import functional_call
        tmpl = self.template
        if tmpl.training != self.training:
            (tmpl.train if self.training else tmpl.eval)()
        stacked = {rel: self._parameters[rel]._data for rel in self._rels}
        carry = x._data if isinstance(x, Tensor) else x
        extras = tuple(e._data if isinstance(e, Tensor) else e
                       for e in extras)

        def body(carry, per_layer):
            bp, key = per_layer
            ctx = (random_mod.key_scope(key) if key is not None
                   else contextlib.nullcontext())
            with ctx:
                out, _ = functional_call(tmpl, bp, {}, carry, *extras,
                                         mutable_state=False)
            return out, None

        # a single trace-time key draw would reuse one dropout mask for
        # every layer — thread per-layer keys through the scan xs instead
        if self.training:
            keys = jax.random.split(random_mod.next_key(), self.num_layers)
        else:
            keys = None

        if self._unroll:
            out = carry
            for i in range(self.num_layers):
                bp = {rel: arr[i] for rel, arr in stacked.items()}
                out, _ = body(out, (bp, None if keys is None else keys[i]))
            return Tensor(out)

        step = body
        if self._recompute:
            step = jax.checkpoint(step, policy=self._recompute_policy)
        if keys is None:
            out, _ = jax.lax.scan(lambda c, bp: step(c, (bp, None)),
                                  carry, stacked)
        else:
            out, _ = jax.lax.scan(step, carry, (stacked, keys))
        return Tensor(out)

    # -- checkpoint layout round-trip ---------------------------------------
    def _expand_state_dict(self, dest, prefix):
        """Replace stacked `{prefix}.{rel}` entries with canonical
        per-block `{prefix}.{i}.{rel}` slices (LayerList naming)."""
        pfx = prefix + "." if prefix else ""
        out = collections.OrderedDict()
        for name, value in dest.items():
            rel = name[len(pfx):] if name.startswith(pfx) else None
            if rel in self._rels:
                for i in range(self.num_layers):
                    out[f"{pfx}{i}.{rel}"] = Tensor(value._data[i])
            else:
                out[name] = value
        return out

    def _collapse_state_dict(self, sd, prefix):
        """Stack incoming per-block `{prefix}.{i}.{rel}` entries into the
        stacked layout; already-stacked entries pass through untouched."""
        import jax.numpy as jnp
        pfx = prefix + "." if prefix else ""
        groups = {}          # rel -> {i: value}
        out = {}
        for name, value in sd.items():
            rel = None
            if name.startswith(pfx) or not pfx:
                tail = name[len(pfx):]
                head, _, r = tail.partition(".")
                if head.isdigit() and r in self._rels:
                    rel, idx = r, int(head)
            if rel is None:
                out[name] = value
                continue
            groups.setdefault(rel, {})[idx] = value
        for rel, by_idx in groups.items():
            if set(by_idx) != set(range(self.num_layers)):
                # partial block set: surface as unexpected keys downstream
                for idx, value in by_idx.items():
                    out[f"{pfx}{idx}.{rel}"] = value
                continue
            arrs = []
            for i in range(self.num_layers):
                v = by_idx[i]
                arrs.append(v._data if isinstance(v, Tensor)
                            else np.asarray(v))
            out[f"{pfx}{rel}"] = jnp.stack(
                [jnp.asarray(a) for a in arrs])
        return out


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None,
                 scan_layers=False):
        super().__init__()
        blocks = [encoder_layer] + [
            _clone_layer(encoder_layer) for _ in range(num_layers - 1)]
        self.layers = (ScanBlockStack(blocks) if scan_layers
                       else LayerList(blocks))
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        if isinstance(self.layers, ScanBlockStack):
            if cache is not None:
                raise NotImplementedError(
                    "incremental decode needs per-layer caches; build the "
                    "encoder with scan_layers=False for cached inference")
            output = self.layers(src, src_mask)
            if self.norm is not None:
                output = self.norm(output)
            return output
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, new_cache = mod(output, src_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        if isinstance(self.layers, ScanBlockStack):
            raise NotImplementedError(
                "gen_cache requires per-layer blocks; build the encoder "
                "with scan_layers=False for cached inference")
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.dropout3 = Dropout(dropout, mode="upscale_in_train")
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                                    cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory,
                                                memory_mask, cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache,
                                                static_cache))

    def gen_cache(self, memory):
        incremental = self.self_attn.gen_cache(memory)
        static = self.cross_attn.gen_cache(memory, memory,
                                           MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([decoder_layer] + [
            _clone_layer(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, new_cache = mod(output, memory, tgt_mask, memory_mask,
                                        cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


class Transformer(Layer):
    """reference: nn/layer/transformer.py Transformer."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    def generate_square_subsequent_mask(self, length):
        import jax.numpy as jnp
        mask = jnp.where(jnp.tril(jnp.ones((length, length), bool)),
                         0.0, -1e9).astype(jnp.float32)
        return Tensor(mask)


def _clone_layer(layer):
    """Fresh re-initialized copy with the same config (reference uses
    copy.deepcopy; fresh init matches since weights are re-initialized
    per layer in the reference construction too)."""
    import copy
    new = copy.copy(layer)
    # deep-copy the stateful dicts, re-initializing parameters
    cls = type(layer)
    if isinstance(layer, TransformerEncoderLayer):
        return cls(layer.self_attn.embed_dim, layer.self_attn.num_heads,
                   layer.linear1._out_features, layer.dropout1.p,
                   _act_name(layer.activation), layer.self_attn.dropout,
                   layer.dropout.p, layer.normalize_before)
    if isinstance(layer, TransformerDecoderLayer):
        return cls(layer.self_attn.embed_dim, layer.self_attn.num_heads,
                   layer.linear1._out_features, layer.dropout1.p,
                   _act_name(layer.activation), layer.self_attn.dropout,
                   layer.dropout.p, layer.normalize_before)
    return copy.deepcopy(layer)


def _act_name(fn):
    return getattr(fn, "__name__", "relu")
