"""Common layers (reference: python/paddle/nn/layer/common.py)."""
from __future__ import annotations

import jax

from ...core import dtype as dtype_mod
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["Linear", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout",
           "Embedding", "Flatten", "Upsample", "UpsamplingBilinear2D",
           "UpsamplingNearest2D", "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D",
           "CosineSimilarity", "Bilinear", "Identity", "PixelShuffle",
           "PixelUnshuffle", "ChannelShuffle", "Unfold", "Fold"]


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b, W: [in_features, out_features] (reference
    nn/layer/common.py Linear; kernel matmul_v2 + elementwise_add)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)
        if bias_attr is False:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = None if padding_idx is None else \
            (padding_idx if padding_idx >= 0 else num_embeddings + padding_idx)
        self._sparse = bool(sparse)
        self._last_ids = None
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if self._padding_idx is not None:
            self.weight._data = self.weight._data.at[self._padding_idx].set(0.0)

    def forward(self, x):
        if self._sparse:
            # remember the touched rows so sparse_grad() can extract a
            # SelectedRows view of the dense tape gradient (on-chip
            # backward stays a dense scatter-add — the XLA-efficient
            # form; SelectedRows is the host/PS interchange format).
            # Ids ACCUMULATE across forwards (grads accumulate too) and
            # reset when sparse_grad() drains them. Tracers (jit) are
            # skipped: there is no host-side grad to pair them with.
            import numpy as np

            from ...core.tensor import Tensor
            raw = x._data if isinstance(x, Tensor) else x
            if isinstance(raw, jax.core.Tracer):
                pass
            else:
                if self._last_ids is None:
                    self._last_ids = []
                self._last_ids.append(np.asarray(raw))
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def sparse_grad(self):
        """SelectedRows over the rows touched since the last drain —
        covers every forward that contributed to the accumulated grad
        (requires sparse=True and a completed backward). Draining resets
        the recorded id set; pair with clear_grad()."""
        from ...core.selected_rows import SelectedRows
        if not self._sparse:
            raise RuntimeError("Embedding(sparse=True) required")
        if self.weight.grad is None or not self._last_ids:
            return None
        import numpy as np
        ids = np.concatenate([np.asarray(i).ravel()
                              for i in self._last_ids])
        self._last_ids = None
        return SelectedRows.from_dense(self.weight.grad.numpy(), ids=ids)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ...ops.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class _PadNd(Layer):
    _n_spatial = 1

    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL"):
        super().__init__()
        if isinstance(padding, int):
            # paddle accepts a bare int: same pad before/after on every
            # spatial dim of the layer's rank
            padding = [padding] * (2 * self._n_spatial)
        self.padding, self.mode = padding, mode
        self.value, self.data_format = value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    _n_spatial = 2

    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    _n_spatial = 3

    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)
        if bias_attr is False:
            self.bias = None

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor, self.data_format = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, *self.args)
