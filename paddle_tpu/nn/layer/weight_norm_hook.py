"""Weight normalization (reference python/paddle/nn/utils/
weight_norm_hook.py): reparameterize a layer's weight as
w = g * v / ||v|| via a forward pre-hook, so the optimizer trains
(g, v) while forward sees the composed weight.

    layer = nn.Linear(4, 8)
    weight_norm(layer)          # adds weight_g / weight_v params
    remove_weight_norm(layer)   # folds back into a plain weight
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, apply
from ...framework import Parameter

__all__ = ["weight_norm", "remove_weight_norm"]


def _norm_except(v, dim):
    """||v|| reduced over every axis except `dim` (dim=None: full norm)."""
    if dim is None:
        return jnp.sqrt(jnp.sum(v * v))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=True))


def _compose(g, v, dim):
    def f(g_, v_):
        n = _norm_except(v_, dim)
        if dim is None:
            return v_ * (g_ / jnp.maximum(n, 1e-12))
        shape = [1] * v_.ndim
        shape[dim] = -1
        return v_ * (g_.reshape(shape) / jnp.maximum(n, 1e-12))
    return apply(f, g, v, op_name="weight_norm")


class _WeightNormHook:
    def __init__(self, name, dim):
        self.name = name
        self.dim = dim

    def __call__(self, layer, inputs):
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        composed = _compose(g, v, self.dim)
        # rebind the composed weight for this forward (not a Parameter:
        # grads flow to g/v through the tape)
        object.__setattr__(layer, self.name, composed)
        return None


def weight_norm(layer, name="weight", dim=0):
    """Apply weight normalization to `layer.<name>` (reference
    weight_norm_hook.weight_norm). dim is the kept axis of the norm
    (None: whole-tensor norm)."""
    if hasattr(layer, name + "_g"):
        raise ValueError(f"weight_norm already applied to {name!r}")
    w = getattr(layer, name)
    w_arr = np.asarray(w.numpy())
    if dim is not None:
        axes = tuple(i for i in range(w_arr.ndim) if i != dim)
        g0 = np.sqrt((w_arr * w_arr).sum(axis=axes))
    else:
        g0 = np.asarray(np.sqrt((w_arr * w_arr).sum()))
    # the original weight Parameter leaves the trainable set; g/v join it
    if name in layer._parameters:
        del layer._parameters[name]
    layer.add_parameter(name + "_g", Parameter(jnp.asarray(g0)))
    layer.add_parameter(name + "_v", Parameter(jnp.asarray(w_arr)))
    hook = _WeightNormHook(name, dim)
    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_handles = getattr(layer, "_weight_norm_handles", {})
    layer._weight_norm_handles[name] = (handle, hook)
    # expose a composed weight immediately (pre-hook refreshes per call)
    object.__setattr__(layer, name,
                       _compose(getattr(layer, name + "_g"),
                                getattr(layer, name + "_v"), dim))
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g/v back into a plain trainable weight (reference
    remove_weight_norm)."""
    handles = getattr(layer, "_weight_norm_handles", {})
    if name not in handles:
        raise ValueError(f"weight_norm not applied to {name!r}")
    handle, hook = handles.pop(name)
    handle.remove()
    g = getattr(layer, name + "_g")
    v = getattr(layer, name + "_v")
    composed = _compose(g, v, hook.dim)
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    if hasattr(layer, name):
        try:
            object.__delattr__(layer, name)
        except AttributeError:
            pass
    layer.add_parameter(name, Parameter(composed._data))
    return layer
