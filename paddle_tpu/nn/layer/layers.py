"""Layer: the module tree (reference: python/paddle/fluid/dygraph/layers.py:76).

Holds parameters/buffers/sublayers, forward/backward hooks, train/eval mode,
state_dict round-trips. The functional bridge (framework.functional_call)
turns any Layer into a pure jittable function.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from ...core import dtype as dtype_mod
from ...core.errors import InvalidArgumentError, enforce
from ...core.tensor import Tensor
from ...framework import Parameter, ParamAttr
from .. import initializer as I


class HookRemoveHelper:
    def __init__(self, hooks, idx):
        self._hooks, self._idx = hooks, idx

    def remove(self):
        self._hooks.pop(self._idx, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype_mod.convert_dtype(dtype)
        self._parameters = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers = collections.OrderedDict()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or type(self).__name__.lower()

    # -- construction -------------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype_mod.convert_dtype(dtype) or self._dtype
        # precedence (reference set_global_initializer contract): an
        # explicit ParamAttr initializer wins; then the global override;
        # then the layer's own default; then the built-ins
        init = attr.initializer or I._global_default(is_bias) \
            or default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        data = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(data, name=attr.name, trainable=attr.trainable)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_variable(self, name=None, persistable=False, dtype=None):
        dtype = dtype_mod.convert_dtype(dtype) or self._dtype
        import jax.numpy as jnp
        return Tensor(jnp.zeros([], dtype), name=name, persistable=persistable)

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise InvalidArgumentError(
                f"add_parameter expects Parameter, got {type(parameter)}")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute protocol -------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
        elif params is not None and name in params:
            if value is None:
                params[name] = None
            elif isinstance(value, Tensor):
                params[name].set_value(value)
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter {name}")
        elif buffers is not None and name in buffers:
            buffers[name] = value
        elif layers is not None and name in layers and value is None:
            del layers[name]
            object.__setattr__(self, name, value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._buffers) + list(self._sub_layers)

    # -- traversal ----------------------------------------------------------
    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, l in self.named_children():
            if l is None or id(l) in layers_set:
                continue
            layers_set.add(id(l))
            sub_prefix = prefix + ("." if prefix else "") + name
            yield sub_prefix, l
            yield from l.named_sublayers(prefix=sub_prefix, layers_set=layers_set)

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        layers = [(prefix, self)]
        if include_sublayers:
            layers += [(n if not prefix else prefix + "." + n, l)
                       for n, l in self.named_sublayers()]
        for lp, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (lp + "." + name if lp else name), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        layers = [(prefix, self)]
        if include_sublayers:
            layers += [(n if not prefix else prefix + "." + n, l)
                       for n, l in self.named_sublayers()]
        for lp, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (lp + "." + name if lp else name), b

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- mode ---------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- execution ----------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = collections.OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers):
            dest[name] = p
        prefix = structured_name_prefix.rstrip(".")
        layers = [(prefix, self)]
        if include_sublayers:
            layers += [(n if not prefix else prefix + "." + n, l)
                       for n, l in self.named_sublayers()]
        seen = set()
        for lp, layer in layers:
            for name, b in layer._buffers.items():
                if (b is None or id(b) in seen
                        or name in layer._non_persistable_buffer_names):
                    continue
                seen.add(id(b))
                dest[(lp + "." + name) if lp else name] = b
        if use_hook:
            # scan-stacked containers (nn.ScanBlockStack) export per-block
            # `{i}.{rel}` entries so checkpoints stay layout-independent
            for lp, layer in layers:
                expand = getattr(layer, "_expand_state_dict", None)
                if expand is not None:
                    dest = expand(dest, lp)
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        # collapse per-block entries back into any scan-stacked container
        # so unrolled checkpoints load into stacked layouts (and vice versa)
        for lp, layer in [("", self)] + list(self.named_sublayers()):
            collapse = getattr(layer, "_collapse_state_dict", None)
            if collapse is not None:
                state_dict = collapse(dict(state_dict), lp)
        own = self.state_dict(use_hook=False)
        missing, unexpected = [], []
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            v = value._data if isinstance(value, Tensor) else np.asarray(value)
            enforce(tuple(np.shape(v)) == tuple(target.shape),
                    f"shape mismatch for {name}: {np.shape(v)} vs {target.shape}")
            target.set_value(v)
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- dtype / device movement -------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        import jax

        from ...core import place as place_mod
        dtype = dtype_mod.convert_dtype(dtype)
        dev = None
        if device is not None:
            if isinstance(device, str):
                from ...core.place import CPUPlace, TPUPlace
                device = CPUPlace() if device == "cpu" else TPUPlace(
                    int(device.split(":")[1]) if ":" in device else 0)
            dev = place_mod._place_to_jax_device(device)
        for t in list(self.parameters()) + list(self.buffers()):
            arr = t._data
            if dtype is not None and dtype_mod.is_floating_point(arr.dtype):
                arr = arr.astype(dtype)
            if dev is not None:
                arr = jax.device_put(arr, dev)
            t._data = arr
        if dtype is not None:
            for l in self.sublayers(include_self=True):
                l._dtype = dtype
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self.named_children():
            child = repr(l).split("\n")
            child = [child[0]] + ["  " + c for c in child[1:]]
            lines.append(f"  ({name}): " + "\n".join(child))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"
