"""Mixture-of-Experts layer with expert parallelism over the 'ep' axis.

The reference snapshot has NO MoE/expert parallelism (SURVEY.md §2
parallelism census: EP absent) — this is a new TPU-native component.

Design: GSPMD-style einsum dispatch (the Mesh-TensorFlow/Switch
formulation). Tokens pick experts by gate logits; a capacity-bounded
dispatch one-hot [tokens, E, C] routes token vectors into per-expert
batches with two einsums. Expert weights are stacked [E, ...] and
sharded P('ep', ...): under jit, XLA partitions the expert dimension and
inserts the all-to-alls — no hand-written collectives, the same
compiler-owned pattern as the rest of the framework. Tokens over
capacity are dropped (standard Switch behavior); an auxiliary
load-balancing loss (Switch-style) is accumulated on the layer.

Routing math is exact w.r.t. the dense equivalent when capacity is
ample, which is what the tests pin.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.tensor import apply
from ..initializer import Normal
from .layers import Layer

__all__ = ["MoELayer", "collect_aux_losses"]

# trace-local collector: GPT.loss (or any training loss) opens this scope
# so every MoE layer's load-balance loss from the CURRENT forward is
# gathered and added to the objective — storing tracers on the layer
# across steps would leak them
_aux_collector = [None]


class collect_aux_losses:
    """with collect_aux_losses() as aux: ...forward...; then sum(aux)."""

    def __enter__(self):
        self._prev = _aux_collector[0]
        _aux_collector[0] = []
        return _aux_collector[0]

    def __exit__(self, *exc):
        _aux_collector[0] = self._prev
        return False


class MoELayer(Layer):
    """Top-k routed FFN experts: y = sum_k gate_k * expert_k(x).

    Input [B, T, M] -> output [B, T, M]. Experts are position-wise FFNs
    (M -> hidden -> M, gelu), weights stacked on a leading E dim.
    """

    def __init__(self, d_model, d_hidden, num_experts, top_k=2,
                 capacity_factor=2.0, name=None):
        super().__init__()
        self.num_experts = int(num_experts)
        self.top_k = int(top_k)
        self.capacity_factor = float(capacity_factor)
        self.d_model = d_model
        self.d_hidden = d_hidden
        init = Normal(0.0, 0.02)
        E = self.num_experts
        self.gate_w = self.create_parameter(
            [d_model, E], default_initializer=init)
        self.w_in = self.create_parameter(
            [E, d_model, d_hidden], default_initializer=init)
        self.b_in = self.create_parameter(
            [E, d_hidden], is_bias=True)
        self.w_out = self.create_parameter(
            [E, d_hidden, d_model], default_initializer=init)
        self.b_out = self.create_parameter(
            [E, d_model], is_bias=True)
        self.aux_loss = None   # set on every forward (load-balance loss)

    # -- strategy-compiler protocol: expert dim rides 'ep' -----------------
    def param_shardings(self, params, mesh_axis_tp="tp", mesh_axis_ep="ep"):
        from jax.sharding import PartitionSpec as P
        specs = {}
        for name, v in params.items():
            nd = len(v.shape)
            if any(name.endswith(s) for s in
                   ("w_in", "b_in", "w_out", "b_out")):
                specs[name] = P(mesh_axis_ep, *([None] * (nd - 1)))
            else:
                specs[name] = P(*([None] * nd))
        return specs

    def forward(self, x):
        E, K = self.num_experts, self.top_k
        M, H = self.d_model, self.d_hidden
        cap_f = self.capacity_factor

        def f(xa, gw, wi, bi, wo, bo):
            B, T, _ = xa.shape
            N = B * T
            C = max(int(math.ceil(cap_f * N * K / E)), 1)
            xt = xa.reshape(N, M)
            logits = (xt @ gw).astype(jnp.float32)          # [N, E]
            probs = jax.nn.softmax(logits, axis=-1)

            # top-k routing with capacity: process the k-th choices in
            # sequence so positions accumulate per expert
            gates_list, onehot_list = [], []
            masked = probs
            for _ in range(K):
                idx = masked.argmax(axis=-1)                # [N]
                oh = jax.nn.one_hot(idx, E, dtype=jnp.float32)
                gates_list.append((probs * oh).sum(-1))     # [N]
                onehot_list.append(oh)
                masked = masked * (1.0 - oh)

            # positions within each expert's capacity, counted across the
            # flattened (k, token) order
            flat_oh = jnp.concatenate(onehot_list, 0)       # [K*N, E]
            pos = jnp.cumsum(flat_oh, axis=0) - flat_oh     # [K*N, E]
            keep = (pos < C) * flat_oh                      # drop overflow
            pos_id = (pos * flat_oh).sum(-1).astype(jnp.int32)   # [K*N]
            cap_oh = jax.nn.one_hot(pos_id, C, dtype=jnp.float32)

            gates = jnp.concatenate(gates_list, 0)          # [K*N]
            # dispatch/combine tensors [K*N, E, C]
            dispatch = keep[:, :, None] * cap_oh[:, None, :]
            combine = dispatch * gates[:, None, None]

            xrep = jnp.tile(xt, (K, 1))                     # [K*N, M]
            expert_in = jnp.einsum("nec,nm->ecm", dispatch,
                                   xrep.astype(jnp.float32))
            h = jnp.einsum("ecm,emh->ech", expert_in,
                           wi.astype(jnp.float32)) + bi[:, None, :]
            h = jax.nn.gelu(h)
            eout = jnp.einsum("ech,ehm->ecm", h,
                              wo.astype(jnp.float32)) + bo[:, None, :]
            y = jnp.einsum("nec,ecm->nm", combine, eout)    # [K*N, M]
            y = y.reshape(K, N, M).sum(0)

            # Switch aux loss: E * sum_e frac_tokens_e * mean_prob_e
            frac = onehot_list[0].mean(0)
            mean_p = probs.mean(0)
            aux = (frac * mean_p).sum() * E
            return y.reshape(B, T, M).astype(xa.dtype), aux

        out, aux = apply(f, x, self.gate_w, self.w_in, self.b_in,
                         self.w_out, self.b_out, op_name="moe")
        if _aux_collector[0] is not None:
            _aux_collector[0].append(aux)
        import jax.core as _core
        if not isinstance(aux._data, _core.Tracer):
            self.aux_loss = aux   # eager convenience; never store tracers
        return out
